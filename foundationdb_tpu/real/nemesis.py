"""Wall-clock nemesis: SLO-asserted chaos campaigns against the real stack.

The sim cluster's DeviceNemesis proves abort-set parity under device
faults in VIRTUAL time; nothing stressed the layers that only exist on
the wall clock — real sockets, reconnect backoff, process supervision,
actual queueing under offered load (ROADMAP item 4). This driver runs a
seeded campaign against the real transport and asserts every SLO by
machine, never by eyeball (docs/real_cluster.md):

  * a wall-clock resolver server (`ChaosCommitServer`): a RealProcess
    serving a commit endpoint over TCP, backed by the SAME supervised
    engine stack production nodes run — ResilientEngine over a
    FaultInjectingEngine over {oracle | jax | device_loop} — with
    per-tenant admission control (server/ratekeeper.TenantAdmission) fed
    a ratekeeper-style degraded-scaled rate;
  * an open-loop Zipfian workload fleet (real/workload.py) driving it
    through `ChaosTransport` shims (real/chaos.py), every client a named
    process the nemesis can partition asymmetrically;
  * a seeded chaos script composing network faults (partitions, drops,
    resets, handshake stalls), device faults (an injected dispatch-fault
    window that must produce a failover AND a swap-back), and process
    kill/restart (a `monitor.Child` demo node killed mid-campaign and
    supervised back up with crash-loop-counted backoff).

After the run, `assert_slos` enforces: client-observed p99 <= the
`resolver_p99_budget_ms` knob OUTSIDE injected-fault windows (via the
span-joined attribution, pipeline/latency_harness helpers); the abort-set
journal replays bit-identical through a clean CPU oracle; loop-mode
`blocking_syncs == 0`; >= 1 failover and >= 1 swap-back; >= 1 supervised
child restart. `make chaos-real` runs this across seeds under both `jax`
and `device_loop` engine modes; `run_served_under_chaos` produces the
bench's Zipf-sweep capacity model (users-served per chip at budget p99,
admission on vs off, nemesis on vs off).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core import blackbox, error, progcache, telemetry
from ..core.knobs import SERVER_KNOBS
from ..core.trace import (
    SPANS_TOKEN,
    TraceContext,
    current_trace_context,
    export_spans,
    g_spans,
    next_trace_id,
    pop_trace_context,
    push_trace_context,
    span_event,
    span_now,
)
from ..tools import trace_export
from ..core.types import CommitTransaction, KeyRange, TransactionCommitResult
from ..sim.network import Endpoint
from .chaos import ChaosConfig, ChaosTransport, NetworkNemesis
from .transport import RealNetwork, RealProcess
from .workload import TenantSpec, WorkloadFleet

COMMIT_TOKEN = "chaos.commit"
STATUS_TOKEN = "chaos.status"

#: version delta per resolved batch and the GC horizon in batches — small
#: enough that shadow rebuilds stay cheap, wide enough that a client whose
#: version cache survives a partition window never goes permanently
#: too-old (clients also refresh their cache off the status endpoint when
#: a too-old verdict tells them they fell behind)
VERSIONS_PER_BATCH = 100
GC_LAG_BATCHES = 400

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _key_range(k) -> KeyRange:
    """Conflict range of one submitted key entry: a point key (bytes)
    covers [k, k\\x00); a (begin, end) pair — the ttl_cache shape's TTL
    sweep — covers the whole [begin, end) segment in ONE range."""
    if isinstance(k, (tuple, list)):
        begin, end = k
        return KeyRange(begin, end)
    return KeyRange(k, k + b"\x00")


def _small_kernel_cfg():
    from ..ops.conflict_kernel import KernelConfig

    # miniature ladder shape: compiles in seconds on CPU, still exercises
    # pack/dispatch/GC exactly like the production shapes
    return KernelConfig(key_words=4, capacity=1024, max_reads=256,
                        max_writes=256, max_txns=64)


def make_chaos_engine(engine_mode: str,
                      dispatch_timeout_s: Optional[float] = None,
                      history_structure: Optional[str] = None):
    """(inner, injector, supervised) for a campaign engine stack.
    `dispatch_timeout_s` overrides the supervisor's per-dispatch
    watchdog: a co-resident CI box stalls the event loop tens to
    hundreds of ms, and a no-fault control campaign (the watchdog
    false-positive guard) must not read such a stall as a device
    fault — operators tune resolver_dispatch_timeout per deployment
    the same way. `history_structure` selects the device history
    layout ("tiered" = the sorted-run interval table, docs/perf.md
    "Incremental history maintenance") — the oracle mode has no device
    table and ignores it."""
    from ..fault.inject import FaultInjectingEngine, FaultRates
    from ..fault.resilient import ResilienceConfig, ResilientEngine

    if engine_mode == "oracle":
        from ..ops.oracle import OracleConflictEngine

        inner = OracleConflictEngine()
    elif engine_mode in ("jax", "device_loop", "mesh"):
        from ..ops.host_engine import make_engine

        # "mesh" spans every visible XLA device (resolver_mesh_devices):
        # a chaos campaign over mesh slots exercises device-shard
        # restart/handoff, not just single-chip rebuilds
        kw = ({"history_structure": history_structure}
              if history_structure else {})
        inner = make_engine(engine_mode, _small_kernel_cfg(), **kw)
    else:
        raise ValueError(f"unknown chaos engine mode {engine_mode!r}")
    injector = FaultInjectingEngine(
        inner, rates=FaultRates(exception=0, hang=0, slow=0, flip=0, outage=0))
    supervised = ResilientEngine(
        injector,
        ResilienceConfig(
            dispatch_timeout=(0.25 if dispatch_timeout_s is None
                              else float(dispatch_timeout_s)),
            retry_budget=1,
            retry_backoff=0.02, probe_rate=0.05,
            probation_batches=2, failover_min_batches=2),
        record_journal=True)
    return inner, injector, supervised


class _GroupInjector:
    """The device-fault control surface of an elastic group: setting
    `rates` fans the FaultRates to EVERY slot's injector (current slots
    at set time — a fault window opens and closes around the same
    population), so a campaign's forced device-fault window targets the
    shards actually serving, not just whichever engine happened to be
    slot 0 before reshards moved the traffic."""

    def __init__(self, group):
        self._group = group
        self._rates = None

    @property
    def rates(self):
        return self._rates

    @rates.setter
    def rates(self, value) -> None:
        self._rates = value
        for slot in self._group.slots.values():
            slot.injector.rates = value


class ChaosCommitServer:
    """The wall-clock resolver node the campaign aims traffic at: commit
    RPCs batch on the cooperative scheduler and resolve in strict version
    order through the supervised engine; admission sheds over-rate tenants
    with the typed transaction_throttled error before they queue."""

    def __init__(self, sched, engine_mode: str = "oracle",
                 admission_tps: Optional[float] = None,
                 admission_burst_s: Optional[float] = None,
                 batch_interval_s: float = 0.004, max_batch: int = 48,
                 service_floor_s: float = 0.0,
                 transport_degraded_fn=None, port: int = 0,
                 dispatch_timeout_s: Optional[float] = None,
                 elastic: bool = False, reshard: bool = False,
                 reshard_spares: int = 2, conflict_sched=None,
                 history_structure: Optional[str] = None):
        from ..server.ratekeeper import TenantAdmission
        from .runtime import make_dispatcher

        self.sched = sched
        self.engine_mode = engine_mode
        self._elastic = elastic
        self._reshard_spares = reshard_spares
        self.reshard_ctl = None
        if elastic:
            # the elastic resolution tier (server/reshard.py): a live
            # group of supervised engines behind an epoched shard map,
            # each built through the SAME make_chaos_engine stack —
            # optionally with the heat-driven resharding controller on
            from ..pipeline.resolver_pipeline import BudgetBatcher
            from ..server.reshard import (ElasticResolverGroup,
                                          ReshardController)

            ladder = sorted({max(8, max_batch // 8), max_batch})
            group = ElasticResolverGroup(
                lambda: make_chaos_engine(
                    engine_mode, dispatch_timeout_s=dispatch_timeout_s,
                    history_structure=history_structure),
                make_batcher=lambda: BudgetBatcher(ladder))
            self.inner, self.engine = group, group
            self.injector = _GroupInjector(group)
            if reshard:
                self.reshard_ctl = ReshardController(
                    group, on_complete=self._on_reshard_complete)
        else:
            self.inner, self.injector, self.engine = make_chaos_engine(
                engine_mode, dispatch_timeout_s=dispatch_timeout_s,
                history_structure=history_structure)
        self.proc = RealProcess(port=port)
        self.proc.dispatcher = make_dispatcher(sched)
        self.proc.register(COMMIT_TOKEN, self._commit)
        self.proc.register(STATUS_TOKEN, self._status)
        # bounded span-ring export (docs/observability.md "Distributed
        # tracing"): tools/cli.py `trace fetch` and the smoke driver pull
        # this process's spans to reconstruct cross-process waterfalls
        self.proc.register(SPANS_TOKEN, self._spans)
        #: span-record recorder label: the process's self-declared name
        #: when it has one (a --serve child), else the in-campaign
        #: logical name — two traced server processes must not collapse
        #: into one indistinguishable pid lane in the Chrome export
        from ..core.trace import process_name

        self._span_proc = process_name() or "server"
        #: the engine's keyspace-heat aggregator (None for the oracle):
        #: the black-box journal's heat briefs and per-batch witness
        #: attribution read through it (core/blackbox.py)
        if elastic:
            self._heat_agg = self.engine.heat
        else:
            self._heat_agg = getattr(self.inner, "heat", None)
        #: conflict-aware admission scheduling (pipeline/scheduler.py):
        #: None = the resolver_sched knob decides; a SchedConfig is used
        #: as-is; any other truthy/falsy value forces enabled on/off over
        #: the knob family's tuning. Disabled, the scheduler is inert —
        #: select() is the same FIFO slice the batcher always took.
        from ..pipeline.scheduler import ConflictScheduler, SchedConfig

        if isinstance(conflict_sched, SchedConfig):
            sched_cfg = conflict_sched
        else:
            sched_cfg = SchedConfig.from_knobs()
            if conflict_sched is not None:
                sched_cfg.enabled = bool(conflict_sched)
        self.conflict_sched = ConflictScheduler(
            sched_cfg, heat=self._heat_agg, entry_txn=lambda e: e[0])
        self.batch_interval_s = batch_interval_s
        self.max_batch = max_batch
        #: injected per-batch service floor: the campaign's stand-in for
        #: device time when modelling capacity (served_under_chaos); 0 for
        #: SLO campaigns (the engine's real cost is the service time)
        self.service_floor_s = service_floor_s
        #: per-tenant admission: None = uncontrolled (the bench's
        #: degradation-demonstration baseline)
        self.admission = (TenantAdmission(burst_s=admission_burst_s)
                          if admission_tps is not None else None)
        self.admission_tps = admission_tps
        if self.admission is not None:
            self.admission.set_rate(admission_tps)
            # the throttle burn-rate rule's good/bad pair (core/watchdog):
            # admitted vs shed totals as `admission.*` hub series
            telemetry.hub().register_admission(self.admission, "admission")
        #: transport-health probe (RealNetClient.transport_degraded on a
        #: wall node with outbound links): collapses the batch cap exactly
        #: like engine degradation — the same hook ResolverPipeline takes
        #: as transport_degraded_fn
        self._transport_degraded_fn = transport_degraded_fn
        self._pending: List[Tuple] = []
        self._version = 0
        self._committed = 0
        self._running = True
        self._batcher_task = None
        self.batches = 0
        self.depth_collapses = 0
        #: crash-stop recovery hooks (fault/recovery.py; the --crash
        #: campaign's recoverable child wires all four): a cadenced
        #: snapshot writer notified per committed batch, the boot-time
        #: recovery arc + tracker served through _status, and the disk
        #: nemesis whose injected-fault inventory explains degraded
        #: snapshot/journal cadence post-hoc
        self.snapshot_mgr = None
        self.recovery_tracker = None
        self.last_recovery: Optional[dict] = None
        self.disk_nemesis = None

    @property
    def degraded(self) -> bool:
        """Engine-degraded OR transport-degraded — either collapses the
        batch cap and tightens admission."""
        if self.engine.degraded:
            return True
        fn = self._transport_degraded_fn
        return bool(fn()) if fn is not None else False

    @property
    def address(self) -> str:
        return self.proc.address

    async def start(self) -> None:
        await self.proc.start()
        from ..sim.loop import TaskPriority

        self._batcher_task = self.sched.spawn(
            self._batcher(), TaskPriority.PROXY_COMMIT_BATCHER,
            name="chaosBatcher")
        if self.reshard_ctl is not None:
            self.reshard_ctl.start(self.sched)

    async def stop(self) -> None:
        self._running = False
        # fail any still-laned entries the batcher will never drain, so
        # no in-flight commit awaits a promise nothing owns anymore
        for _t, p, _t0, _m in self.conflict_sched.flush():
            if not p.is_set:
                p.send_error(error.operation_cancelled(""))
        if self.reshard_ctl is not None:
            self.reshard_ctl.stop()
        if self._batcher_task is not None:
            self._batcher_task.cancel()
        await self.proc.stop()

    def warmup(self) -> None:
        """AOT-compile the ladder for device-backed modes so the campaign
        never charges first-compile stalls to the SLO window; an elastic
        group additionally pre-warms standby recipient engines so a
        reshard never compiles on the serving path."""
        fn = getattr(self.engine, "warmup", None)
        if fn is not None and self.engine_mode != "oracle":
            fn()
        if self._elastic:
            self.engine.prewarm_spares(self._reshard_spares)

    def _on_reshard_complete(self, op) -> None:
        """Mid-flight adaptation after a cutover: per-tenant admission
        weights rebalance from the post-reshard heat fractions, so the
        published rate's split tracks where the load actually moved
        (server/reshard.py rebalance_admission)."""
        from ..server.reshard import rebalance_admission

        if self.admission is not None:
            rebalance_admission(self.admission, self.engine.heat)

    # -- handlers (run on the cooperative scheduler via the dispatcher) ------
    async def _commit(self, body):
        from ..sim.loop import Promise, now

        # distributed tracing: the inbound context must be captured in the
        # synchronous prefix (before the first await — core/trace.py's
        # scheduler-dispatch discipline). The server.commit span emitted on
        # every exit path carries the resolved commit VERSION as the link
        # detail the waterfall reconstruction joins batch spans on.
        ctx = current_trace_context() if g_spans.enabled else None
        t_recv = span_now() if ctx is not None else 0.0
        tenant, reads, writes, snapshot = body
        if self.admission is not None and not self.admission.admit(tenant, now()):
            if ctx is not None:
                span_event("server.commit", ctx.trace_id, t_recv, span_now(),
                           parent=ctx.parent, err="transaction_throttled",
                           tenant=tenant, Proc=self._span_proc)
            raise error.transaction_throttled(f"tenant {tenant}")
        # a key entry is a point key (bytes) or a (begin, end) RANGE pair
        # (TTL sweeps — workload.py TxnShaper "ttl_cache"): one conflict
        # range either way, so range deletes cost one interval-table row
        txn = CommitTransaction(
            read_snapshot=int(snapshot),
            read_conflict_ranges=[_key_range(k) for k in reads],
            write_conflict_ranges=[_key_range(k) for k in writes])
        p = Promise()
        #: meta cell: the batcher writes the batch's commit version here
        #: before dispatch, so even a conflicted/too-old verdict's server
        #: span can name the version that judged it. Only allocated for
        #: traced requests — the disabled path stays allocation-free.
        meta: Optional[Dict[str, int]] = {} if ctx is not None else None
        self._pending.append((txn, p, now(), meta))
        try:
            v = await p.future
        except error.FDBError as e:
            if (e.name == "transaction_conflict_predicted"
                    and self.admission is not None):
                # a pre-abort consumed no device capacity: hand the
                # admission token back so the client's fresh-version
                # retry isn't double-charged (server/ratekeeper.py)
                self.admission.refund(tenant)
            if ctx is not None:
                span_event("server.commit", ctx.trace_id, t_recv, span_now(),
                           parent=ctx.parent, err=e.name,
                           version=meta.get("version"), tenant=tenant,
                           Proc=self._span_proc)
            raise
        if ctx is not None:
            span_event("server.commit", ctx.trace_id, t_recv, span_now(),
                       parent=ctx.parent, version=int(v), tenant=tenant,
                       Proc=self._span_proc)
        return v

    async def _spans(self, _body):
        return export_spans()

    async def _status(self, _body):
        out = {
            "engine_mode": self.engine_mode,
            "committed_version": self._committed,
            "batches": self.batches,
            "depth_collapses": self.depth_collapses,
            "health": self.engine.health_stats(),
            "admission": (self.admission.as_dict()
                          if self.admission is not None else None),
            "shed_expired": self.proc.shed_expired,
        }
        if self.reshard_ctl is not None:
            out["reshard"] = self.reshard_ctl.snapshot()
        if self.conflict_sched.enabled:
            out["sched"] = self.conflict_sched.snapshot()
        loop_stats = getattr(self.inner, "loop_stats", None)
        if loop_stats is not None:
            out["loop_stats"] = dict(loop_stats)
        if self.last_recovery is not None:
            out["recovery"] = self.last_recovery
        if self.snapshot_mgr is not None:
            out["snapshots"] = dict(self.snapshot_mgr.stats)
        if self.disk_nemesis is not None:
            out["disk"] = self.disk_nemesis.summary()
        if progcache.enabled():
            out["progcache"] = progcache.active().summary()
        if blackbox.enabled():
            out["blackbox"] = blackbox.active().summary()
        return out

    # -- the serial resolve loop ---------------------------------------------
    def _refresh_admission(self) -> None:
        """Ratekeeper-style feed: the published admission rate scales by
        the degraded fraction while the supervised engine is unhealthy —
        the same signal path Ratekeeper._update_rate applies cluster-wide."""
        if self.admission is None or self.admission_tps is None:
            return
        frac = (float(SERVER_KNOBS.resolver_degraded_tps_fraction)
                if self.degraded else 1.0)
        if self._elastic and self.engine.reshard_in_flight:
            # reshard clamp (server/ratekeeper.py's tps_reshard, applied
            # at the campaign's admission point): handoff work and the
            # frozen range's queueing must not race full-rate admission
            frac = min(frac, float(SERVER_KNOBS.reshard_tps_fraction))
        self.admission.set_rate(self.admission_tps * frac)

    async def _batcher(self) -> None:
        from ..sim.loop import TaskPriority, delay, now

        committed = int(TransactionCommitResult.COMMITTED)
        hub = telemetry.hub()
        # watchdog heartbeat (core/watchdog.py): the batcher is the
        # campaign's live pulse, so alerts fire DURING the run, not at
        # the autopsy — but a full hub.sync() re-renders every
        # registered series, and the fastest burn window is 0.5 s, so
        # evaluating every ~64 ms loses nothing while keeping that host
        # work off the 4 ms measured batch cadence. One attribute check
        # per tick when the watchdog is off — the disabled path is free.
        wd_stride = max(1, round(0.064 / max(self.batch_interval_s, 1e-4)))
        ticks = 0
        while self._running:
            await delay(self.batch_interval_s, TaskPriority.PROXY_COMMIT_BATCHER)
            ticks += 1
            if hub.watchdog is not None and ticks % wd_stride == 0:
                hub.sync()
            if ticks % wd_stride == 0 and blackbox.enabled():
                # low-rate observability heartbeat onto the journal: the
                # admission/shed totals and the heat brief `cli explain`
                # joins a version against (same cadence as the watchdog —
                # one list-index check per tick when the journal is off)
                if self.admission is not None:
                    adm = self.admission
                    blackbox.record_admission(
                        "admission", sum(adm.admitted.values()),
                        sum(adm.rejected.values()),
                        rate=(float(adm.rate_limit)
                              if adm.rate_limit != float("inf") else 0.0),
                        weights=adm.weights)
                if self._heat_agg is not None:
                    blackbox.record_heat(self._heat_agg.brief())
            sched = self.conflict_sched
            if not self._pending and not sched.pending_laned():
                continue
            self._refresh_admission()
            # depth/batch collapse on degradation: a degraded engine or
            # transport serves smallest batches at depth 1 — mirroring
            # ResilientEngine's pipeline collapse — so recovery work
            # stays bounded
            cap = self.max_batch
            if self.degraded:
                cap = max(1, self.max_batch // 8)
                self.depth_collapses += 1
            plan = None
            if sched.enabled:
                if self._elastic:
                    # lanes were derived under the current shard map; an
                    # epoch flip drains and retires them so no laned
                    # transaction straddles two map generations
                    sched.notify_epoch(self.engine.emap.epoch)
                t_sel = span_now()
                plan = sched.select(self._pending, cap)
                self._pending = plan.remaining
                batch = plan.dispatch
                for (_t, p, _t0, _m), rng in plan.preaborts:
                    if not p.is_set:
                        p.send_error(error.transaction_conflict_predicted(
                            f"range {rng.hex()}"))
                if g_spans.enabled and (batch or plan.preaborts):
                    span_event("sched.select", self._version, t_sel,
                               span_now(), txns=len(batch),
                               preaborts=len(plan.preaborts),
                               Proc=self._span_proc)
                if not batch:
                    continue
            else:
                batch = self._pending[:cap]
                del self._pending[:cap]
            self._version += VERSIONS_PER_BATCH
            v = self._version
            new_oldest = max(0, v - GC_LAG_BATCHES * VERSIONS_PER_BATCH)
            txns = [t for t, _p, _t0, _m in batch]
            t_open = min(t0 for _t, _p, t0, _m in batch)
            for _t, _p, _t0, meta in batch:
                # link every traced member's request to this batch BEFORE
                # dispatch: a faulted verdict still names its version
                if meta is not None:
                    meta["version"] = v
            t0 = span_now()
            try:
                verdicts = await self.engine.resolve(txns, v, new_oldest)
            except error.FDBError as e:
                for _t, p, _t0, _m in batch:
                    if not p.is_set:
                        p.send_error(e)
                continue
            if self.service_floor_s > 0:
                # capacity model: the serial service slot is occupied for
                # the injected floor, exactly like a device program would
                await delay(self.service_floor_s,
                            TaskPriority.PROXY_COMMIT_BATCHER)
            t1 = span_now()
            self.batches += 1
            self._committed = v
            if self.snapshot_mgr is not None:
                # crash-stop recovery cadence: snapshot the engine's
                # coalesced shadow every N committed versions (never
                # raises into the serving path — fault/recovery.py)
                self.snapshot_mgr.note_batch(self.engine, v)
            if sched.enabled:
                # close the prediction loop: committed writes stamp
                # last-write versions, conflicts bump range scores, and
                # dispatched probes resolve to probe_ok/mispredict
                sched.observe_batch(txns, verdicts, v)
                if plan is not None and blackbox.enabled():
                    blackbox.record_sched(
                        plan, v, len(sched.lanes),
                        len(self._pending) + sched.pending_laned(),
                        epoch=sched.epoch)
            if not self._elastic and blackbox.enabled():
                # non-elastic: the commit server IS the resolution tier's
                # top level, so it records the batch (an elastic group
                # records its own inside _resolve_impl, with the epoch)
                blackbox.record_batch(
                    txns, v, new_oldest, verdicts,
                    engine=self.engine_mode,
                    served_by=getattr(self.engine, "state", ""),
                    witness=(self._heat_agg.attribution_for(v)
                             if self._heat_agg is not None else ()),
                    proc=self._span_proc)
            if g_spans.enabled:
                span_event("chaos.queue_wait", v, t_open, t0, txns=len(txns),
                           Proc=self._span_proc)
                span_event("chaos.resolve", v, t0, t1, txns=len(txns),
                           Proc=self._span_proc)
            for (txn, p, _t0, _m), verdict in zip(batch, verdicts):
                if p.is_set:
                    continue   # deadline-shed by the transport meanwhile
                if int(verdict) == committed:
                    p.send(v)
                elif int(verdict) == int(TransactionCommitResult.TOO_OLD):
                    p.send_error(error.transaction_too_old(""))
                else:
                    p.send_error(error.not_committed(""))


@dataclass
class NemesisConfig:
    """One seeded wall-clock campaign."""

    seed: int = 11
    engine_mode: str = "oracle"
    duration_s: float = 4.0
    #: None = the resolver_p99_budget_ms knob
    budget_ms: Optional[float] = None
    tenants: Optional[List[TenantSpec]] = None
    #: per-tenant admission on? (None = on, at 1.2x total offered)
    admission: bool = True
    admission_tps: Optional[float] = None
    #: None = the tenant_admission_burst_s knob
    admission_burst_s: Optional[float] = None
    rpc_timeout_s: float = 1.0
    max_batch: int = 48
    service_floor_s: float = 0.0
    #: network nemesis
    chaos: Optional[ChaosConfig] = None
    partitions: int = 1
    partition_s: float = 0.6
    #: device-fault window (forced failover -> swap-back round trip)
    device_faults: bool = True
    #: kill + supervised restart of a monitor.Child demo node
    kill_child: bool = True
    child_backoff_s: float = 0.3
    collect_spans: bool = True
    #: write this campaign's tail-sampled cross-process Chrome trace JSON
    #: here (None = no file; the report's trace summary is kept either way)
    trace_export: Optional[str] = None
    batch_interval_s: float = 0.004
    #: cold-start grace excluded from the SLO as a recorded window, the
    #: wall-clock analog of the sim harness's warmup_frac head-drop:
    #: first connects, first batches and cold engine paths are warmup,
    #: not steady-state serving
    warmup_frac: float = 0.15
    #: cluster watchdog (core/watchdog.py): None = the watchdog_enabled
    #: knob decides; True/False force-attach/detach for this campaign.
    #: With it on, the report gains `alerts` + `incidents` and
    #: `assert_slos` additionally requires every firing incident to be
    #: EXPLAINED (overlap an injected fault window or name a breach)
    watchdog: Optional[bool] = None
    #: extra AlertRule instances appended to the default ruleset (tests
    #: induce an unexplained incident through this)
    watchdog_extra_rules: Optional[list] = None
    #: supervisor per-dispatch watchdog override (None = the campaign
    #: default, 0.25 s). Control campaigns on co-resident CI boxes
    #: widen it so an event-loop stall can't masquerade as a device
    #: fault (see make_chaos_engine)
    dispatch_timeout_s: Optional[float] = None
    #: elastic resolution tier (server/reshard.py): the commit server
    #: resolves through an ElasticResolverGroup of supervised engines
    #: behind an epoched shard map instead of one engine
    elastic: bool = False
    #: heat-driven online resharding controller active (implies elastic)
    reshard: bool = False
    #: pre-warmed standby recipient engines (reshards never compile on
    #: the serving path while a spare is available)
    reshard_spares: int = 2
    #: assert_slos floor on executed reshards (the drift campaign's >= 2)
    min_reshards: int = 0
    #: durable black-box journal directory (core/blackbox.py): None =
    #: the resolver_blackbox knob decides; "" forces off; a path turns
    #: the journal on there — the report then carries a `blackbox`
    #: summary and `cli explain <version> REPORT.json` narrates any
    #: resolved version post-hoc
    blackbox_dir: Optional[str] = None
    #: conflict-aware admission scheduler (pipeline/scheduler.py): None =
    #: the resolver_sched knob decides; True/False force it on/off for
    #: this campaign. On, the report carries a `sched` snapshot and the
    #: fleet's submit loop retries transaction_conflict_predicted with a
    #: refreshed read version (the pre-abort contract, docs/scheduling.md)
    sched: Optional[bool] = None
    #: scenario-atlas stamp (real/scenarios.py): the named recipe this
    #: campaign instantiates. Stamped into the report, the heat/abort
    #: SIGNATURE computed while the black-box journal is still installed
    #: (a `scenario` event), and `scenario.<name>.*` telemetry gauges —
    #: None keeps the pre-atlas campaign byte-identical
    scenario: Optional[str] = None
    #: device history layout for the campaign engines ("tiered" = the
    #: sorted-run interval table, docs/perf.md "Incremental history
    #: maintenance"); None keeps the monolithic table. Oracle mode has
    #: no device table and ignores it
    history_structure: Optional[str] = None

    #: budget multiplier for CPU-emulated device modes: a real chip-
    #: adjacent resolver serves a batch in well under a millisecond, but
    #: the CPU-backed jax/device_loop engines pay ~7-19 ms per small batch
    #: on a CI box — the campaign budgets that service floor honestly
    #: instead of pretending the emulation is the chip
    DEVICE_MODE_BUDGET_FACTOR = 3.0

    def resolved_budget_ms(self) -> float:
        """The asserted budget: explicit override, or the budget-knob
        product resolver_p99_budget_ms x real_chaos_budget_factor (the
        wall-clock serving point; see the knob's rationale). CPU-emulated
        device modes scale once more for their ~10 ms/batch service."""
        base = (float(self.budget_ms) if self.budget_ms is not None
                else float(SERVER_KNOBS.resolver_p99_budget_ms)
                * float(SERVER_KNOBS.real_chaos_budget_factor))
        if self.engine_mode != "oracle":
            base *= self.DEVICE_MODE_BUDGET_FACTOR
        return base

    def resolved_batch_interval_s(self) -> float:
        # device-backed modes coalesce harder: fewer, fuller batches keep
        # utilization sane against the ~10 ms CPU-emulated service time
        if self.engine_mode != "oracle":
            return max(self.batch_interval_s, 0.008)
        return self.batch_interval_s

    def default_tenants(self) -> List[TenantSpec]:
        """Default fleet sized for the in-process wall-clock ensemble: the
        transport's serial RTT is ~1 ms of CPU per request on a CI box, so
        ~110 offered txn/s keeps utilization low enough that the SLO
        measures the system, not event-loop saturation (the sweep's
        overload points raise this deliberately). Device-backed engine
        modes scale down further — their CPU-emulated service time is
        ~10x the oracle's."""
        if self.tenants is not None:
            return self.tenants
        scale = 1.0 if self.engine_mode == "oracle" else 0.4
        return [
            TenantSpec("hot", target_tps=45 * scale, s=1.2, n_keys=256),
            TenantSpec("warm", target_tps=35 * scale, s=0.9, n_keys=512),
            TenantSpec("uniform", target_tps=30 * scale, s=0.0, n_keys=1024),
        ]


@dataclass
class CampaignReport:
    cfg_seed: int
    engine_mode: str
    p99_outside_ms: float = float("nan")
    n_outside: int = 0
    p99_overall_ms: float = float("nan")
    counts: Dict[str, int] = field(default_factory=dict)
    sustained_tps: float = 0.0
    windows: List[Tuple[float, float]] = field(default_factory=list)
    engine_stats: Dict[str, int] = field(default_factory=dict)
    parity_checked: int = 0
    parity_mismatches: int = 0
    loop_stats: Optional[dict] = None
    #: keyspace heat & occupancy snapshot (core/heatmap.py) of the
    #: campaign engine — lets `cli heat REPORT.json` correlate SLO
    #: breaches with hot-key pressure after the fact
    heat: Optional[dict] = None
    admission: Optional[dict] = None
    child_restarts: int = 0
    child_crash_count: int = 0
    child_pingable_after: bool = False
    chaos_counts: Dict[str, int] = field(default_factory=dict)
    suffered: Dict[str, Dict[str, int]] = field(default_factory=dict)
    transport: Dict[str, int] = field(default_factory=dict)
    attribution: Optional[dict] = None
    #: watchdog alert lifecycle states at campaign end (core/watchdog.py)
    alerts: Optional[list] = None
    #: machine-correlated incident timeline: firing alerts grouped and
    #: matched against injected fault windows, health transitions and the
    #: trace root cause — `cli incidents REPORT.json` renders it and
    #: assert_slos requires every entry explained
    incidents: Optional[list] = None
    #: tail-sampled waterfall population (tools/trace_export.trace_summary)
    traces: Optional[dict] = None
    #: dominant segment of the worst retained trace — what an SLO-breach
    #: report names first (tools/trace_export.root_cause)
    slo_root_cause: Optional[dict] = None
    #: path of the exported Chrome trace JSON (None = not written)
    trace_file: Optional[str] = None
    #: black-box journal summary (core/blackbox.py BlackboxJournal
    #: .summary(): dir, event/segment counts, version range) — the
    #: handle `cli explain` / `cli blackbox` resolve a report through
    blackbox: Optional[dict] = None
    depth_collapses: int = 0
    shed_expired: int = 0
    #: online-resharding controller snapshot (server/reshard.py): epoch
    #: chain, executed/stalled ops with per-op blackouts — `cli shards
    #: REPORT.json` renders it
    reshard: Optional[dict] = None
    #: per-executed-reshard blackout durations as measured by the
    #: reshard.blackout trace segments (the PR 8 span verification of the
    #: blackout SLO, independent of the controller's own clocks)
    reshard_span_blackouts_ms: Optional[list] = None
    #: post-reshard per-tenant admission weights (rebalance_admission)
    admission_weights: Optional[dict] = None
    #: conflict scheduler snapshot (pipeline/scheduler.py
    #: ConflictScheduler.snapshot()): decision counters, lane states,
    #: predictor hot ranges and the mispredict fraction — `cli sched
    #: REPORT.json` renders it
    sched: Optional[dict] = None
    #: scenario-atlas stamp (real/scenarios.py): which named recipe this
    #: campaign ran (None on pre-atlas / unnamed campaigns — `cli atlas`
    #: renders the absence as "—", never a KeyError)
    scenario: Optional[str] = None
    #: the scenario's heat/abort signature (real/scenarios.py
    #: build_signature): load concentration, top-range shares, verdict
    #: and witness mix — recorded into the black-box journal too
    signature: Optional[dict] = None
    wall_s: float = 0.0

    def as_dict(self) -> dict:
        out = dict(self.__dict__)
        out["windows"] = [(round(a, 4), round(b, 4)) for a, b in self.windows]
        return out


def replay_journal_parity(journal) -> Tuple[int, int]:
    """Replay the supervised engine's journal through a CLEAN reference
    oracle: the emitted abort sets must be bit-identical to a fault-free
    engine's for the same batch stream (the DeviceNemesis contract, now on
    the wall clock). Returns (batches checked, mismatches)."""
    from ..ops.oracle import OracleConflictEngine

    clean = OracleConflictEngine()
    checked = mismatches = 0
    for version, txns, new_oldest, verdicts in journal or []:
        want = clean.resolve(list(txns), version, new_oldest)
        checked += 1
        if [int(x) for x in want] != [int(x) for x in verdicts]:
            mismatches += 1
    return checked, mismatches


def _attribute_spans(acks, budget_ms: float) -> Optional[dict]:
    """Join client acks to the server's per-batch spans by commit version:
    the server-side queue_wait/resolve segments must nest inside the
    client-observed latency (the residual is network + marshalling), and
    the p99 the SLO asserts is computed over the SAME span-joined rows."""
    by_trace = g_spans.durations_by_trace()
    rows = []
    for t0, lat, ok, version in acks:
        if not ok or version is None:
            continue
        tr = by_trace.get(version)
        if tr is None or "chaos.resolve" not in tr:
            continue
        rows.append((lat, tr.get("chaos.queue_wait", 0.0), tr["chaos.resolve"]))
    if not rows:
        return None
    from ..pipeline.latency_harness import percentile_index

    rows.sort(key=lambda r: r[0])
    lat, qw, rs = rows[percentile_index(len(rows), 0.99)]
    return {
        "n_attributed": len(rows),
        "p99": {
            "client_ms": round(lat * 1e3, 4),
            "server_queue_wait_ms": round(qw * 1e3, 4),
            "server_resolve_ms": round(rs * 1e3, 4),
            "net_residual_ms": round((lat - qw - rs) * 1e3, 4),
        },
        "budget_ms": budget_ms,
    }


async def _wait_for(predicate, timeout_s: float, interval_s: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval_s)
    return predicate()


def _child_argv(port: int) -> List[str]:
    code = ("import sys; sys.path.insert(0, %r); "
            "from foundationdb_tpu.real.demo_server import main; "
            "sys.exit(main(['--port', '%d']))" % (REPO_ROOT, port))
    return [sys.executable, "-c", code]


async def _ping_child(port: int, timeout_s: float = 0.5) -> bool:
    from .demo_server import PING_TOKEN

    net = RealNetwork(name="nemesis-prober")
    try:
        r = await net.request("prober", Endpoint(f"127.0.0.1:{port}", PING_TOKEN),
                              7, timeout=timeout_s)
        return r == 7
    except (error.FDBError, ConnectionError, OSError):
        return False
    finally:
        net.close()


async def _child_chaos(cfg: NemesisConfig, report: CampaignReport,
                       log_dir: str,
                       windows_out: List[Tuple[float, float]]) -> None:
    """Process-layer nemesis: spawn a demo node under monitor.Child, kill
    it mid-campaign, and let the supervision policy (crash-loop counter +
    backoff, real/monitor.py) bring it back; prove it serves again.

    Both churn phases (initial spawn->up and kill->restarted) are recorded
    as fault windows: on a small CI box a fresh Python child's import
    storm steals a core from the serving loop, and that CPU contention IS
    part of the injected process-kill incident, not steady state."""
    from .cluster import free_ports
    from .monitor import Child, poll_children

    (port,) = free_ports(1)
    child = Child("node.chaos", _child_argv(port))
    child.backoff = cfg.child_backoff_s   # campaign-paced restart
    t_spawn = time.monotonic()
    child.spawn(log_dir)
    try:
        up = False
        for _ in range(100):
            if await _ping_child(port):
                up = True
                break
            await asyncio.sleep(0.1)
        windows_out.append((t_spawn, time.monotonic()))
        if not up:
            return   # child never served; report stays at zero restarts
        telemetry.hub().chaos_event("process_kill", port=port)
        t_kill = time.monotonic()
        child.proc.kill()
        # supervise it back up: poll_children applies the backoff + crash
        # counter; the restart must NOT be hot (due() gates on restart_at)
        deadline = time.monotonic() + cfg.child_backoff_s * 10 + 5
        while time.monotonic() < deadline:
            poll_children([child], log_dir)
            if child.restarts >= 1 and await _ping_child(port):
                report.child_pingable_after = True
                telemetry.hub().chaos_event("process_restart", port=port)
                break
            await asyncio.sleep(0.1)
        windows_out.append((t_kill, time.monotonic()))
        report.child_restarts = child.restarts
        report.child_crash_count = max(child.crash_count, report.child_crash_count)
    finally:
        child.stop()


async def _device_chaos(cfg: NemesisConfig, server: ChaosCommitServer) \
        -> List[Tuple[float, float]]:
    """Force the failover -> swap-back round trip: open a dispatch-fault
    window on the injector until the supervisor fails over to the CPU
    oracle, close it, then wait for probation to swap the device back.
    The EXCLUDED window spans the whole failover -> swap-back arc: the
    recovery (shadow rebuild, device re-warm, probation double-resolve) is
    part of the injected incident, and graceful degradation through it is
    asserted via journal parity + error accounting, not the p99 budget."""
    from ..fault.inject import FaultRates

    eng, injector = server.engine, server.injector
    t0 = time.monotonic()
    telemetry.hub().chaos_event("device_fault_window", engine=cfg.engine_mode)
    injector.rates = FaultRates(exception=0.95, hang=0, slow=0, flip=0,
                                outage=0, applied_fraction=0.5)
    await _wait_for(lambda: eng.stats["failovers"] >= 1, timeout_s=3.0)
    injector.rates = FaultRates(exception=0, hang=0, slow=0, flip=0, outage=0)
    # swap-back needs failover_min_batches on the oracle + clean probation
    # batches; traffic is still flowing, so just wait for the supervisor
    await _wait_for(lambda: eng.stats["swap_backs"] >= 1, timeout_s=8.0)
    if eng.stats["swap_backs"] >= 1:
        telemetry.hub().chaos_event("device_swap_back", engine=cfg.engine_mode)
    return [(t0, time.monotonic())]


def _campaign_blackbox(cfg: NemesisConfig):
    """This campaign's black-box journal, or None. An explicit
    cfg.blackbox_dir is used verbatim (main() already makes it
    per-campaign); the `resolver_blackbox` knob path gets a
    `<mode>_s<seed>` SUBDIRECTORY of the knob directory — campaigns
    restart versions at 0 every run, so a multi-campaign invocation
    sharing one directory would wipe every earlier campaign's journal
    (each report's blackbox.dir must survive the whole run). Either way
    the journal opens fresh=True: a re-run into the same deterministic
    path truncates the previous colliding stream."""
    proc = f"{cfg.engine_mode}-s{cfg.seed}"
    if cfg.blackbox_dir is not None:
        if not cfg.blackbox_dir:
            return None
        return blackbox.BlackboxJournal(cfg.blackbox_dir, proc=proc,
                                        fresh=True)
    base = blackbox.knob_directory()
    if base is None:
        return None
    return blackbox.BlackboxJournal(
        os.path.join(base, f"{cfg.engine_mode}_s{cfg.seed}"), proc=proc,
        fresh=True)


async def _campaign(cfg: NemesisConfig) -> CampaignReport:
    import gc

    from ..core import buggify
    from ..sim.loop import set_scheduler
    from .runtime import RealScheduler

    # a sim that ran earlier in this process (pytest co-residency) may
    # have left BUGGIFY enabled; the wall-clock campaign is a MEASURED
    # run — leaked sim fault injection (e.g. the ResilientEngine
    # dispatch-boundary site) would fail over healthy engines and charge
    # phantom incidents/latency to the system under test
    buggify_rng = buggify._rng
    buggify_was = buggify.is_enabled()
    buggify.disable()
    telemetry.reset()
    # cluster watchdog (core/watchdog.py): cfg override wins, else the
    # watchdog_enabled knob (telemetry.reset() already auto-attached a
    # default-ruleset engine when the knob is on). Campaign-attached
    # engines get the default catalog plus any test-induced extras.
    wd = None
    use_watchdog = (cfg.watchdog if cfg.watchdog is not None
                    else telemetry.hub().watchdog is not None)
    if use_watchdog:
        from ..core import watchdog as watchdog_mod

        wd = watchdog_mod.Watchdog(
            list(watchdog_mod.default_rules())
            + list(cfg.watchdog_extra_rules or []))
    telemetry.hub().attach_watchdog(wd)
    wd_budget_ms = cfg.resolved_budget_ms()
    # Defer cyclic GC for the measured window: at ~100 rps of RPC frames,
    # futures and span records, a gen-2 collection stalls the event loop
    # 20-50 ms on a CI box — latency that belongs to CPython, not the
    # system under test. Real latency-sensitive Python services ship the
    # same tuning; re-enabled (with a collect) in the finally.
    gc.collect()
    gc_was_enabled = gc.isenabled()
    gc.disable()
    spans_were = g_spans.enabled
    if cfg.collect_spans:
        g_spans.enabled = True
        g_spans.clear()
    # durable black-box journal (core/blackbox.py): explicit campaign dir
    # wins, else the resolver_blackbox knob; one journal per campaign so
    # `cli explain` resolves a report to exactly its own event stream
    bb = _campaign_blackbox(cfg)
    if bb is not None:
        blackbox.install(bb)
    report = CampaignReport(cfg_seed=cfg.seed, engine_mode=cfg.engine_mode)
    t_campaign = time.monotonic()
    sched = RealScheduler(seed=cfg.seed)
    set_scheduler(sched)
    run_task = asyncio.ensure_future(sched.run_async())
    tenants = cfg.default_tenants()
    offered_tps = sum(t.target_tps for t in tenants)
    admission_tps = (cfg.admission_tps if cfg.admission_tps is not None
                     else offered_tps * 1.2) if cfg.admission else None
    server = ChaosCommitServer(
        sched, engine_mode=cfg.engine_mode, admission_tps=admission_tps,
        admission_burst_s=cfg.admission_burst_s,
        batch_interval_s=cfg.resolved_batch_interval_s(),
        max_batch=cfg.max_batch,
        service_floor_s=cfg.service_floor_s,
        dispatch_timeout_s=cfg.dispatch_timeout_s,
        elastic=cfg.elastic or cfg.reshard, reshard=cfg.reshard,
        reshard_spares=cfg.reshard_spares, conflict_sched=cfg.sched,
        history_structure=cfg.history_structure)
    nemesis = NetworkNemesis(cfg.seed, cfg.chaos)
    transports: Dict[str, ChaosTransport] = {}
    versions: Dict[str, int] = {}
    log_dir = tempfile.mkdtemp(prefix="fdb_tpu_nemesis_")
    incident_windows: List[Tuple[float, float]] = []
    try:
        await server.start()
        server.warmup()
        addr = server.address
        commit_ep = Endpoint(addr, COMMIT_TOKEN)
        status_ep = Endpoint(addr, STATUS_TOKEN)
        for t in tenants:
            name = f"client-{t.name}"
            transports[t.name] = ChaosTransport(
                RealNetwork(name=name), nemesis, name=name)
            versions[t.name] = 0
        refreshing: Dict[str, bool] = {t.name: False for t in tenants}

        async def refresh_version(tenant: str) -> None:
            # a too-old verdict means this tenant's cached snapshot fell
            # behind the GC horizon (e.g. it sat out a partition); refetch
            # the committed version off the status endpoint — through the
            # SAME chaos transport, so a partitioned tenant stays stale
            # until the window heals (honest degradation)
            if refreshing.get(tenant):
                return
            refreshing[tenant] = True
            try:
                st = await transports[tenant].request(
                    f"client-{tenant}", status_ep, None,
                    timeout=cfg.rpc_timeout_s)
                versions[tenant] = max(versions[tenant],
                                       int(st["committed_version"]))
            except (error.FDBError, ConnectionError, OSError):
                pass
            finally:
                refreshing[tenant] = False

        async def submit(spec: TenantSpec, reads, writes):
            # pre-abort contract (docs/scheduling.md): the scheduler's
            # transaction_conflict_predicted reject is a fast retryable
            # error issued BEFORE device dispatch — the client refreshes
            # its read version off the status endpoint and resubmits.
            # Bounded so a mispredicting predictor cannot livelock a
            # client; exhaustion reports as a conflict (not_committed),
            # the honest verdict class for a txn the predictor deemed
            # un-commitable at every snapshot it was offered.
            for _attempt in range(8):
                try:
                    return await submit_once(spec, reads, writes)
                except error.FDBError as e:
                    if e.name != "transaction_conflict_predicted":
                        raise
                    await refresh_version(spec.name)
            raise error.not_committed("sched_retry_exhausted")

        async def submit_once(spec: TenantSpec, reads, writes):
            # distributed tracing: one context per request, attached to the
            # RPC frame by the transport and RE-ATTACHED verbatim on any
            # retry (the ambient context is re-read per send), so the
            # serving process's spans join this request's trace. Gated on
            # the span switch — with tracing off, nothing allocates.
            ctx = None
            if g_spans.enabled:
                ctx = TraceContext(trace_id=next_trace_id(),
                                   parent="client.commit")
                tok = push_trace_context(ctx)
                t_sub = span_now()
            t_wd = time.monotonic() if wd is not None else 0.0
            try:
                v = await transports[spec.name].request(
                    f"client-{spec.name}", commit_ep,
                    (spec.name, reads, writes, versions[spec.name]),
                    timeout=cfg.rpc_timeout_s)
            except error.FDBError as e:
                if ctx is not None:
                    span_event("client.commit", ctx.trace_id, t_sub,
                               span_now(), err=e.name, tenant=spec.name,
                               Proc=f"client-{spec.name}")
                if wd is not None and e.name in ("not_committed",
                                                 "transaction_too_old"):
                    # a verdict-bearing ack: it counts against the p99
                    # SLO exactly like the harness's ack population
                    # (throttles/transport failures burn other budgets)
                    watchdog_mod.record_commit_sli(
                        telemetry.hub(),
                        (time.monotonic() - t_wd) * 1e3, wd_budget_ms)
                if e.name == "transaction_too_old":
                    asyncio.ensure_future(refresh_version(spec.name))
                raise
            finally:
                if ctx is not None:
                    pop_trace_context(tok)
            if ctx is not None:
                span_event("client.commit", ctx.trace_id, t_sub, span_now(),
                           version=int(v), tenant=spec.name,
                           Proc=f"client-{spec.name}")
            if wd is not None:
                watchdog_mod.record_commit_sli(
                    telemetry.hub(), (time.monotonic() - t_wd) * 1e3,
                    wd_budget_ms)
            versions[spec.name] = max(versions[spec.name], int(v))
            return int(v)

        fleet = WorkloadFleet(tenants, submit, seed=cfg.seed,
                              duration_s=cfg.duration_s)

        async def chaos_script():
            rng = nemesis.rng
            # stagger the composed faults across the run
            await asyncio.sleep(cfg.duration_s * 0.15)
            tasks = []
            if cfg.kill_child:
                tasks.append(asyncio.ensure_future(
                    _child_chaos(cfg, report, log_dir, incident_windows)))
            for _ in range(max(0, cfg.partitions)):
                victim = tenants[rng.random_int(0, len(tenants))]
                nemesis.partition(f"client-{victim.name}", addr,
                                  cfg.partition_s)
                await asyncio.sleep(cfg.duration_s * 0.15)
            if cfg.device_faults:
                incident_windows.extend(await _device_chaos(cfg, server))
            if tasks:
                await asyncio.gather(*tasks)

        script = asyncio.ensure_future(chaos_script())
        rep = await fleet.run()
        # keep a trickle flowing until the swap-back/child scripts finish
        # (the fleet window may end mid-probation)
        while not script.done():
            try:
                await submit(tenants[-1], [b"tick/000001"], [b"tick/000001"])
            except error.FDBError:
                pass
            await asyncio.sleep(0.05)
        await script
        # post-recovery cooldown: a RECORDED steady-state phase after every
        # injected incident has closed, so the SLO always has a meaningful
        # outside-window population even when a slow recovery arc (e.g. a
        # dragged swap-back under co-resident load) ate the main window
        cooldown = WorkloadFleet(
            tenants, submit, seed=cfg.seed + 1,
            duration_s=max(1.0, cfg.duration_s * 0.3), report=rep)
        await cooldown.run()

        from ..pipeline.latency_harness import percentile_outside_windows

        # no padding: exclusion is by ack-lifetime INTERSECTION with the
        # windows (percentile_outside_windows), so an in-flight request
        # caught by a window is excluded without blanket padding
        windows = nemesis.fault_windows()
        windows += incident_windows
        #: reshard blackouts are PLANNED maintenance windows with their
        #: own SLO (per-range blackout <= reshard_blackout_budget_ms,
        #: asserted separately): acks caught inside one are excluded from
        #: the p99 like injected faults, and the watchdog correlates
        #: incidents against them under their own window kind. The
        #: `reshard_arc` records (whole plan -> cutover handoff) are
        #: correlation-only — the service keeps serving through the arc,
        #: so its latency stays IN the p99 population; only the blackout
        #: and any inline-warm window are excluded
        reshard_windows: List[dict] = (list(server.reshard_ctl.windows)
                                       if server.reshard_ctl is not None
                                       else [])
        windows += [(w["t0"], w["t1"]) for w in reshard_windows
                    if w["kind"] != "reshard_arc"]
        if cfg.warmup_frac > 0:
            # cold-start grace (see NemesisConfig.warmup_frac)
            windows.append((rep.t_start,
                            rep.t_start + cfg.duration_s * cfg.warmup_frac))
        # kinded window records: the nemesis' own (partition/stall/...)
        # plus the composed device/process arcs and the warmup grace —
        # shared by the Chrome trace export AND watchdog incident
        # correlation, so both views name the same injected faults
        window_dicts = list(nemesis.windows)
        window_dicts += [{"kind": "device_incident", "t0": a, "t1": b}
                         for a, b in incident_windows]
        window_dicts += reshard_windows
        if cfg.warmup_frac > 0:
            window_dicts.append({
                "kind": "warmup", "t0": rep.t_start,
                "t1": rep.t_start + cfg.duration_s * cfg.warmup_frac})
        if blackbox.enabled():
            # the injected fault inventory onto the journal: explain's
            # "overlapping faults" join reads the same kinded records
            # the SLO exclusion and the watchdog correlation use
            for w in window_dicts:
                blackbox.record_window(w)
        acks = rep.ack_records()
        report.windows = windows
        report.counts = rep.counts()
        report.sustained_tps = round(rep.sustained_tps(), 1)
        report.p99_outside_ms, report.n_outside = \
            percentile_outside_windows(acks, windows, p=0.99)
        from ..pipeline.latency_harness import percentile_ms

        report.p99_overall_ms = percentile_ms(
            sorted(l * 1e3 for _t, l, _ok, _v in acks), 0.99)
        report.engine_stats = dict(server.engine.stats)
        parity_fn = getattr(server.engine, "parity_check", None)
        if parity_fn is not None:
            # elastic group: every shard engine's journal — handoff
            # adoption batches included — replays through its own clean
            # oracle (server/reshard.py parity_check)
            report.parity_checked, report.parity_mismatches = parity_fn()
        else:
            report.parity_checked, report.parity_mismatches = \
                replay_journal_parity(server.engine.journal)
        heat_fn = getattr(server.engine, "heat_snapshot", None)
        if heat_fn is not None:
            report.heat = heat_fn()
        loop_stats = getattr(server.inner, "loop_stats", None)
        if loop_stats is not None:
            # quiesce the loop before reading sync accounting
            server.engine.clear(0)
            report.loop_stats = dict(loop_stats)
        report.admission = (server.admission.as_dict()
                            if server.admission is not None else None)
        if server.conflict_sched.enabled:
            report.sched = server.conflict_sched.snapshot()
        if server.reshard_ctl is not None:
            report.reshard = server.reshard_ctl.snapshot()
            if server.admission is not None:
                report.admission_weights = dict(server.admission.weights)
        report.chaos_counts = telemetry.hub().chaos_counts()
        report.suffered = {name: dict(tr.suffered)
                           for name, tr in transports.items()}
        report.transport = {
            "reconnects": sum(tr.inner.reconnects for tr in transports.values()),
            "backoff_failfasts": sum(tr.inner.backoff_failfasts
                                     for tr in transports.values()),
        }
        report.depth_collapses = server.depth_collapses
        report.shed_expired = server.proc.shed_expired
        if cfg.collect_spans:
            report.attribution = _attribute_spans(
                [r for r in acks
                 if not any(r[0] <= w1 and r[0] + r[1] >= w0
                            for w0, w1 in windows)],
                cfg.resolved_budget_ms())
            # cross-process waterfalls + tail-sampled trace export
            # (docs/observability.md "Distributed tracing"): reconstruct
            # every request's client->server->resolve waterfall, retain
            # the p99 candidates and every faulted/throttled/retried
            # request, name the worst offender's dominant segment (what
            # an assert_slos breach leads with), and write the Chrome
            # trace-event JSON with the nemesis fault windows on the
            # same timeline
            spans = list(g_spans.spans)
            if server.reshard_ctl is not None:
                # the span-verified blackout SLO: every executed reshard
                # emitted one reshard.blackout segment carrying its
                # measured freeze -> cutover duration
                report.reshard_span_blackouts_ms = [
                    rec.get("blackout_ms") for rec in spans
                    if rec.get("Name") == "reshard.blackout"]
            waterfalls = trace_export.build_waterfalls(spans)
            retained = trace_export.tail_sample(waterfalls)
            report.traces = trace_export.trace_summary(waterfalls, retained)
            report.slo_root_cause = trace_export.root_cause(retained)
            # the tail-sampled span set, shared by the journal sink and
            # the Chrome export below (one filter pass, two consumers)
            retained_spans = trace_export.spans_for_traces(spans, retained)
            if blackbox.enabled():
                # span records PAST the tail sampler onto the journal —
                # the retained waterfalls (p99 candidates + every faulted
                # request) are the per-request half explain joins batch
                # records against; unretained clean acks stay ring-only
                for rec in retained_spans:
                    blackbox.record_span(rec)
        if wd is not None:
            # final evaluation tick, then machine-correlate: every firing
            # incident must overlap an injected fault window, carry the
            # health transitions it spans, and name the dominant latency
            # segment of the worst retained trace — "slo_p99_burn firing ·
            # overlaps partition window · dominant=server_resolve"
            telemetry.hub().sync()
            breached = ("p99_budget"
                        if report.p99_outside_ms > wd_budget_ms else None)
            wd.correlate(window_dicts, root_cause=report.slo_root_cause,
                         breached_slo=breached)
            report.alerts = wd.alerts_snapshot()
            report.incidents = [i.as_dict() for i in wd.incidents]
        if cfg.collect_spans and cfg.trace_export:
            # Chrome export AFTER the watchdog correlation so incident
            # windows render on their own `watchdog` track next to the
            # nemesis fault track and the reshard arcs — one timeline
            # shows faults, incidents and reshards together
            export_windows = list(window_dicts)
            for inc in report.incidents or []:
                export_windows.append({
                    "kind": "incident", "t0": inc["t0"],
                    "t1": (inc["t1"] if inc["t1"] is not None
                           else inc["t0"]),
                    "summary": inc.get("summary")})
            doc = trace_export.chrome_trace(retained_spans, export_windows)
            os.makedirs(os.path.dirname(os.path.abspath(cfg.trace_export)),
                        exist_ok=True)
            with open(cfg.trace_export, "w") as f:
                json.dump(doc, f, default=str)
            report.trace_file = cfg.trace_export
        if cfg.scenario is not None:
            # scenario-atlas stamp (real/scenarios.py): the recipe name
            # + the heat/abort signature ride the report, the
            # `scenario.<name>.*` telemetry gauges, and — while the
            # journal is still installed — a black-box `scenario` event,
            # so post-hoc forensics can answer "which production shape
            # was this run?" from the journal alone
            from .scenarios import build_signature, publish_scenario

            report.scenario = cfg.scenario
            report.signature = build_signature(report)
            publish_scenario(cfg.scenario, report)
            if blackbox.enabled():
                blackbox.record_scenario(cfg.scenario, cfg.seed,
                                         cfg.engine_mode, report.signature)
        if bb is not None:
            report.blackbox = bb.summary()
    finally:
        if bb is not None:
            blackbox.uninstall()
        if buggify_was and buggify_rng is not None:
            buggify.enable(buggify_rng)
        if gc_was_enabled:
            gc.enable()
            gc.collect()
        for tr in transports.values():
            tr.close()
        await server.stop()
        sched.shutdown()
        run_task.cancel()
        set_scheduler(None)
        g_spans.enabled = spans_were
    report.wall_s = round(time.monotonic() - t_campaign, 2)
    return report


def run_campaign(cfg: NemesisConfig) -> CampaignReport:
    return asyncio.run(_campaign(cfg))


def assert_slos(report: CampaignReport, cfg: NemesisConfig,
                min_outside: int = 50) -> None:
    """Machine-assert every campaign SLO; raises AssertionError with the
    full report on any breach (docs/real_cluster.md, 'SLO contract')."""
    budget = cfg.resolved_budget_ms()
    ctx = json.dumps(report.as_dict(), default=str)
    if cfg.scenario is not None:
        # scenario-atlas stamp integrity (real/scenarios.py): a NAMED
        # campaign must carry its name and heat/abort signature — the
        # scenario's own budget rows (abort/throttle fractions, witness
        # mix) are then asserted by scenarios.assert_scenario_slos on top
        assert report.scenario == cfg.scenario, \
            f"scenario stamp lost ({report.scenario!r}): {ctx}"
        assert report.signature, \
            f"scenario {cfg.scenario} recorded no signature: {ctx}"
    assert report.parity_checked > 0, f"no journal batches to replay: {ctx}"
    assert report.parity_mismatches == 0, \
        f"abort sets NOT bit-identical to the clean oracle: {ctx}"
    assert report.n_outside >= min_outside, \
        (f"only {report.n_outside} acks outside fault windows "
         f"(need >= {min_outside} for a meaningful p99): {ctx}")
    root = report.slo_root_cause or {}
    assert report.p99_outside_ms <= budget, \
        (f"p99 outside injected-fault windows {report.p99_outside_ms:.3f} ms "
         f"exceeds budget {budget} ms — worst retained trace's dominant "
         f"segment: {root.get('dominant_segment')} "
         f"({root.get('dominant_ms')} ms of {root.get('client_ms')} ms, "
         f"trace {root.get('rid')} v{root.get('version')}): {ctx}")
    if cfg.device_faults:
        assert report.engine_stats.get("failovers", 0) >= 1, \
            f"no failover observed: {ctx}"
        assert report.engine_stats.get("swap_backs", 0) >= 1, \
            f"no swap-back observed: {ctx}"
    if cfg.engine_mode in ("device_loop", "mesh"):
        assert report.loop_stats is not None, f"no loop stats: {ctx}"
        assert report.loop_stats.get("blocking_syncs", 0) == 0, \
            f"{cfg.engine_mode} ring fell back to a blocking sync: {ctx}"
    if cfg.kill_child:
        assert report.child_restarts >= 1, \
            f"supervised child never restarted: {ctx}"
        assert report.child_pingable_after, \
            f"restarted child never served again: {ctx}"
    if cfg.partitions > 0:
        assert report.chaos_counts.get("partition", 0) >= 1, \
            f"no partition was injected: {ctx}"
    if cfg.reshard:
        # resharding SLOs (docs/elasticity.md "Blackout SLO"): enough
        # reshards actually EXECUTED on the live cluster, none stalled,
        # and every per-range blackout within budget — by the controller's
        # own clocks AND by the independent reshard.blackout trace segments
        rs = report.reshard or {}
        bo_budget = float(SERVER_KNOBS.reshard_blackout_budget_ms)
        assert rs.get("executed", 0) >= cfg.min_reshards, \
            (f"only {rs.get('executed', 0)} reshards executed "
             f"(need >= {cfg.min_reshards}): {ctx}")
        assert rs.get("stalled", 0) == 0, \
            f"{rs.get('stalled')} reshard(s) stalled: {ctx}"
        for op in rs.get("ops", []):
            if op.get("state") == "done":
                assert op["blackout_ms"] <= bo_budget, \
                    (f"reshard #{op['id']} ({op['kind']}) blackout "
                     f"{op['blackout_ms']:.1f} ms exceeds budget "
                     f"{bo_budget} ms: {ctx}")
        if cfg.collect_spans:
            bos = report.reshard_span_blackouts_ms or []
            assert len(bos) >= rs.get("executed", 0), \
                (f"{len(bos)} reshard.blackout trace segments for "
                 f"{rs.get('executed')} executed reshards: {ctx}")
            assert all(b is not None and b <= bo_budget for b in bos), \
                f"span-measured blackout over budget {bo_budget} ms: {ctx}"
    if report.sched is not None:
        # conflict-scheduler SLOs (docs/scheduling.md): the scheduler saw
        # the campaign's traffic, and once the probe population is big
        # enough to mean anything, the measured mispredict fraction stays
        # inside the same budget the sched_mispredict watchdog rule burns
        sc = report.sched
        assert sc["counters"].get("examined", 0) > 0, \
            f"scheduler enabled but examined no transactions: {ctx}"
        probes = (sc["counters"].get("probe_ok", 0)
                  + sc["counters"].get("mispredicts", 0))
        frac_budget = float(SERVER_KNOBS.resolver_sched_mispredict_frac)
        if probes >= 20:
            assert sc["mispredict_frac"] <= frac_budget, \
                (f"scheduler mispredict fraction "
                 f"{sc['mispredict_frac']:.3f} exceeds {frac_budget} "
                 f"over {probes} probes: {ctx}")
    if report.incidents is not None:
        # every firing incident must be EXPLAINED: it overlaps an
        # injected fault window or names a measured breach. An alert
        # with neither is the watchdog crying wolf — or a real
        # regression the campaign didn't inject; both fail the run,
        # alert name first so the log reads like a page.
        for inc in report.incidents:
            lead = (inc.get("alerts") or [{"name": "incident"}])[0]["name"]
            assert inc.get("explained"), \
                (f"{lead}: firing incident #{inc.get('id')} "
                 f"({inc.get('summary')}) is not explained by any "
                 f"injected fault window or named breach: {ctx}")
    if cfg.collect_spans:
        assert report.attribution is not None, \
            f"span attribution empty (spans not collected?): {ctx}"
        tr = report.traces or {}
        assert tr.get("retained", 0) >= 1, \
            f"tail sampling retained no traces: {ctx}"
        # the completeness contract: every retained verdict-bearing ack
        # (p99 candidate or faulted) reconstructs a COMPLETE cross-process
        # waterfall — only transport-failed requests may be client-only
        assert tr.get("retained_ack_incomplete", 0) == 0, \
            (f"{tr.get('retained_ack_incomplete')} retained ack(s) lack a "
             f"complete waterfall: {ctx}")
        assert tr.get("max_sum_err_ms", 0.0) <= 0.05, \
            (f"waterfall segments do not sum to client latency "
             f"(max err {tr.get('max_sum_err_ms')} ms): {ctx}")


# -- the diurnal drift campaign (online resharding under moving load) ---------

def drift_config(seed: int, engine_mode: str = "oracle",
                 duration_s: Optional[float] = None,
                 **kw) -> NemesisConfig:
    """The live-elasticity campaign (ROADMAP item 4, docs/elasticity.md):
    an open-loop Zipf fleet whose hot range DRIFTS across the keyspace
    over the run, served by the elastic resolver group with the
    heat-driven resharding controller active, composed with background
    NetworkNemesis faults. assert_slos then additionally requires >= 2
    reshards executed on the live cluster with every per-range blackout
    inside `reshard_blackout_budget_ms` (span-verified), on top of the
    standard p99/parity/incident contract."""
    if duration_s is None:
        duration_s = 6.0 if engine_mode == "oracle" else 10.0
    scale = 1.0 if engine_mode == "oracle" else 0.4
    n_keys = 512
    tenants = [
        # the drifting hot tenant: its Zipf head sweeps most of the pool
        # over the campaign, so the load concentration MOVES through the
        # key-sorted space and a static partition goes stale
        TenantSpec("drift", target_tps=55 * scale, s=1.2, n_keys=n_keys,
                   drift_keys_per_s=n_keys * 0.6 / duration_s),
        TenantSpec("warm", target_tps=25 * scale, s=0.9, n_keys=512),
        TenantSpec("bg", target_tps=20 * scale, s=0.0, n_keys=1024),
    ]
    kw.setdefault("watchdog", True)
    return NemesisConfig(
        seed=seed, engine_mode=engine_mode, duration_s=duration_s,
        tenants=tenants, elastic=True, reshard=True, min_reshards=2,
        partitions=1, partition_s=0.4,
        device_faults=False, kill_child=False, **kw)


# -- the bench capacity model -------------------------------------------------

def run_served_under_chaos(skews=(0.0, 0.9, 1.2), seconds: float = 4.0,
                           seed: int = 2026,
                           txns_per_user_per_sec: float = 0.5,
                           budget_ms: Optional[float] = None) -> dict:
    """The Zipf-sweep capacity model (bench.py `served_under_chaos`):
    per skew s, run the SAME overloaded wall-clock serving point with
    per-tenant admission ON and OFF under an active network nemesis. The
    capacity line: admission holds admitted-traffic p99 inside the budget
    by shedding over-rate arrivals as fast typed errors; the uncontrolled
    run queues them instead and blows the budget — measured, not assumed.
    `users_served_per_chip` converts the in-budget sustained rate at the
    reference skew (0.9) into users at `txns_per_user_per_sec`, with and
    without the nemesis."""
    if budget_ms is None:
        budget_ms = (float(SERVER_KNOBS.resolver_p99_budget_ms)
                     * float(SERVER_KNOBS.real_chaos_budget_factor))
    # capacity model point: one serial service slot of `floor_s` per batch,
    # batch cap 1 -> capacity ~= 1/(floor + tick). Offered runs ~1.3x OVER
    # capacity, so the uncontrolled queue grows without bound and p99
    # blows decisively; admission at 0.5x capacity holds M/D/1 queueing to
    # a few service times AND yields enough admitted acks that the p99 is
    # robust to a stray scheduler hiccup. The floor is the
    # wall-clock stand-in for device time — the absolute tps is transport-
    # bound and deliberately small (docs/real_cluster.md).
    floor_s, max_batch = 0.008, 1
    capacity_tps = max_batch / (floor_s + 0.0004)
    offered_total = 1.3 * capacity_tps
    admit_tps = 0.5 * capacity_tps

    def point(s: float, admission: bool, nemesis_on: bool, pseed: int) -> dict:
        tenants = [
            TenantSpec("hot", target_tps=offered_total * 0.6, s=s, n_keys=256),
            TenantSpec("bg", target_tps=offered_total * 0.4, s=0.0, n_keys=1024),
        ]
        chaos = ChaosConfig() if nemesis_on else ChaosConfig(
            latency_prob=0, drop_prob=0, reset_prob=0, handshake_stall_prob=0)
        cfg = NemesisConfig(
            seed=pseed, engine_mode="oracle", duration_s=seconds,
            budget_ms=budget_ms, tenants=tenants, admission=admission,
            admission_tps=admit_tps if admission else None,
            admission_burst_s=0.05,   # a burst must not fill the slot
            rpc_timeout_s=30.0,   # honest queueing latencies, not timeouts
            batch_interval_s=0.0004, max_batch=max_batch,
            service_floor_s=floor_s, chaos=chaos,
            partitions=1 if nemesis_on else 0, partition_s=0.4,
            device_faults=False, kill_child=False, collect_spans=False)
        rep = run_campaign(cfg)
        counts = rep.counts
        offered = max(counts.get("offered", 0), 1)
        served = counts.get("committed", 0) + counts.get("conflicted", 0)
        row = {
            "s": s,
            "admission": admission,
            "nemesis": nemesis_on,
            "p99_ms": round(rep.p99_outside_ms, 3),
            "p99_overall_ms": round(rep.p99_overall_ms, 3),
            "in_budget": bool(rep.p99_outside_ms <= budget_ms),
            "sustained_tps": rep.sustained_tps,
            "offered": offered,
            "served": served,
            "throttled_frac": round(counts.get("throttled", 0) / offered, 3),
            "abort_frac": round(counts.get("conflicted", 0) / max(served, 1), 3),
        }
        return row

    sweep = []
    for i, s in enumerate(skews):
        for admission in (True, False):
            sweep.append(point(s, admission, nemesis_on=True,
                               pseed=seed + i * 10 + int(admission)))
    ref_s = 0.9 if 0.9 in skews else skews[0]
    baseline = point(ref_s, True, nemesis_on=False, pseed=seed + 97)
    under = next(r for r in sweep if r["s"] == ref_s and r["admission"])
    users = {
        "no_nemesis": (round(baseline["sustained_tps"] / txns_per_user_per_sec)
                       if baseline["in_budget"] else 0),
        "under_nemesis": (round(under["sustained_tps"] / txns_per_user_per_sec)
                          if under["in_budget"] else 0),
    }
    return {
        "budget_ms": budget_ms,
        "txns_per_user_per_sec": txns_per_user_per_sec,
        "capacity_model_tps": round(capacity_tps),
        "offered_tps": round(offered_total),
        "admitted_tps_target": round(admit_tps),
        "sweep": sweep,
        "baseline_no_nemesis": baseline,
        "users_served_per_chip": users,
    }


#: budget multiplier for the ELASTIC serving point, the
#: DEVICE_MODE_BUDGET_FACTOR precedent: the group's host-side routing,
#: dedup cache, group-heat accounting and (while resharding) pre-copy
#: replay all share the CI box's cores with the modeled 8 ms service
#: slot, and measured run-to-run p99 swings tens of ms from co-resident
#: contention alone. A chip-adjacent deployment runs those on the donor
#: engine's own host thread; the budget prices the emulation honestly
#: instead of letting scheduler noise zero the capacity figure.
ELASTIC_BUDGET_FACTOR = 2.0


def run_served_while_resharding(seconds: float = 6.0, seed: int = 2027,
                                txns_per_user_per_sec: float = 0.5,
                                budget_ms: Optional[float] = None) -> dict:
    """The elastic capacity model (ROADMAP item 4 follow-up, bench.py
    `served_while_resharding`): the SAME modeled serving point as
    `run_served_under_chaos` (one 8 ms service slot per batch, admission
    at half capacity), but served through the elastic resolver group
    under a DRIFTING Zipf hot spot — once with the heat-driven reshard
    controller ACTIVE (ranges split/move live, admission clamps to
    `reshard_tps_fraction` while a handoff is in flight, blackouts pause
    the frozen range) and once static. `users_served_per_chip` converts
    each in-budget sustained rate into users at `txns_per_user_per_sec`,
    so the artifact answers: what does live resharding cost the serving
    capacity, measured, vs. the static 104-107 users/chip figure?"""
    if budget_ms is None:
        budget_ms = (float(SERVER_KNOBS.resolver_p99_budget_ms)
                     * float(SERVER_KNOBS.real_chaos_budget_factor)
                     * ELASTIC_BUDGET_FACTOR)
    # the run_served_under_chaos capacity point — one serial service slot
    # of floor_s per batch — but offered at 0.9x and admitted at 0.4x
    # capacity instead of its 1.3x/0.5x: the while-resharding row must
    # measure the PROTOCOL's cost (admission clamp, blackout stalls, the
    # moved history), not M/D/1 queueing amplified by CI-box CPU
    # contention at the saturation knee
    floor_s, max_batch = 0.008, 1
    capacity_tps = max_batch / (floor_s + 0.0004)
    offered_total = 0.9 * capacity_tps
    admit_tps = 0.4 * capacity_tps

    def point(reshard: bool, pseed: int) -> dict:
        n_keys = 512
        tenants = [
            # the drifting hot tenant: its Zipf head sweeps the key pool
            # so a static partition goes stale mid-run (the drift
            # campaign's load shape at the capacity point's rates)
            TenantSpec("drift", target_tps=offered_total * 0.6, s=1.2,
                       n_keys=n_keys,
                       drift_keys_per_s=n_keys * 0.6 / seconds),
            TenantSpec("bg", target_tps=offered_total * 0.4, s=0.0,
                       n_keys=1024),
        ]
        cfg = NemesisConfig(
            seed=pseed, engine_mode="oracle", duration_s=seconds,
            budget_ms=budget_ms, tenants=tenants, admission=True,
            admission_tps=admit_tps, admission_burst_s=0.05,
            rpc_timeout_s=30.0, batch_interval_s=0.0004,
            max_batch=max_batch, service_floor_s=floor_s,
            chaos=ChaosConfig(latency_prob=0, drop_prob=0, reset_prob=0,
                              handshake_stall_prob=0),
            partitions=0, device_faults=False, kill_child=False,
            collect_spans=False, elastic=True, reshard=reshard,
            reshard_spares=1)
        rep = run_campaign(cfg)
        counts = rep.counts
        offered = max(counts.get("offered", 0), 1)
        served = counts.get("committed", 0) + counts.get("conflicted", 0)
        rs = rep.reshard or {}
        return {
            "reshard": reshard,
            "p99_ms": round(rep.p99_outside_ms, 3),
            "in_budget": bool(rep.p99_outside_ms <= budget_ms),
            "sustained_tps": rep.sustained_tps,
            "offered": offered,
            "served": served,
            "throttled_frac": round(counts.get("throttled", 0) / offered, 3),
            "abort_frac": round(counts.get("conflicted", 0)
                                / max(served, 1), 3),
            "reshards_executed": rs.get("executed", 0),
            "reshards_stalled": rs.get("stalled", 0),
            "blackout_ms_max": rs.get("blackout_ms_max", 0.0),
            "final_shards": (rs.get("shard_map") or {}).get("n_shards"),
            "parity_checked": rep.parity_checked,
            "parity_mismatches": rep.parity_mismatches,
        }

    static = point(False, seed)
    resharding = point(True, seed + 1)

    def users(row: dict) -> int:
        return (round(row["sustained_tps"] / txns_per_user_per_sec)
                if row["in_budget"] else 0)

    return {
        "budget_ms": budget_ms,
        "txns_per_user_per_sec": txns_per_user_per_sec,
        "capacity_model_tps": round(capacity_tps),
        "offered_tps": round(offered_total),
        "admitted_tps_target": round(admit_tps),
        "static": static,
        "resharding": resharding,
        "users_served_per_chip": {
            "static": users(static),
            "while_resharding": users(resharding),
        },
    }


def run_conflict_scheduling(seconds: float = 4.0, seed: int = 3026) -> dict:
    """The conflict-scheduling A/B (bench.py `conflict_scheduling`): the
    SAME contended Zipf-1.2 wall-clock serving point with the conflict
    scheduler OFF and ON, same seeds, same fleet. The claim under test
    (docs/scheduling.md): pre-abort + refresh-and-retry plus hot-range
    serialization lanes at least HALVE the abort fraction at equal-or-
    better served txn/s — aborts become fast early rejects the client
    retries at a fresh snapshot instead of wasted device verdicts. Both
    rows replay their engine journal through a clean serial oracle: the
    scheduler reorders ADMISSION, never resolution, so parity must hold
    bit-for-bit in the scheduled order too."""

    def point(sched_on: bool, pseed: int) -> dict:
        # a contention-dominated point, NOT a capacity-dominated one: a
        # small hot key pool under Zipf 1.2 makes write-write collisions
        # the limiting factor while the serving slot stays uncongested,
        # so abort_frac measures conflict handling, not queueing
        tenants = [
            TenantSpec("hot", target_tps=200, s=1.2, n_keys=16),
            TenantSpec("bg", target_tps=25, s=0.0, n_keys=1024),
        ]
        cfg = NemesisConfig(
            seed=pseed, engine_mode="oracle", duration_s=seconds,
            tenants=tenants, admission=True,
            rpc_timeout_s=30.0, batch_interval_s=0.002, max_batch=48,
            chaos=ChaosConfig(latency_prob=0, drop_prob=0, reset_prob=0,
                              handshake_stall_prob=0),
            partitions=0, device_faults=False, kill_child=False,
            collect_spans=False, sched=sched_on)
        rep = run_campaign(cfg)
        counts = rep.counts
        offered = max(counts.get("offered", 0), 1)
        served = counts.get("committed", 0) + counts.get("conflicted", 0)
        row = {
            "sched": sched_on,
            "p99_ms": round(rep.p99_outside_ms, 3),
            "sustained_tps": rep.sustained_tps,
            "offered": offered,
            "committed": counts.get("committed", 0),
            "conflicted": counts.get("conflicted", 0),
            "served": served,
            "served_tps": round(served / max(seconds, 1e-9), 1),
            "throttled_frac": round(counts.get("throttled", 0) / offered, 3),
            "abort_frac": round(counts.get("conflicted", 0)
                                / max(served, 1), 4),
            "parity_checked": rep.parity_checked,
            "parity_mismatches": rep.parity_mismatches,
        }
        if rep.sched is not None:
            sc = rep.sched["counters"]
            row["preaborts"] = sc.get("preaborts", 0)
            row["laned"] = sc.get("laned", 0)
            row["deferred"] = sc.get("deferred", 0)
            row["probes"] = sc.get("probes", 0)
            row["mispredict_frac"] = rep.sched["mispredict_frac"]
        return row

    # same seed both arms: identical arrival processes, so the delta is
    # the scheduler, not sampling noise
    off = point(False, seed)
    on = point(True, seed)
    reduction = (1.0 - on["abort_frac"] / off["abort_frac"]
                 if off["abort_frac"] > 0 else 0.0)
    return {
        "off": off,
        "on": on,
        "abort_frac_reduction": round(reduction, 3),
        "served_tps_ratio": round(on["served_tps"]
                                  / max(off["served_tps"], 1e-9), 3),
        "goal_met": bool(
            reduction >= 0.5
            and on["served_tps"] >= off["served_tps"] * 0.98
            and on["parity_mismatches"] == 0
            and off["parity_mismatches"] == 0),
    }


# -- solo traced commit server (the 2-process trace smoke's child) ------------

async def _serve_commit(port: int) -> None:
    """Run ONE traced ChaosCommitServer solo: the child half of `make
    trace-smoke`'s 2-OS-process cluster. Spans are on and the process
    names itself, so fetched span rings identify their recorder."""
    from ..core.trace import set_process_name, set_span_collection
    from ..sim.loop import set_scheduler
    from .runtime import RealScheduler

    set_span_collection(True)
    set_process_name(f"commit-server:{port}")
    sched = RealScheduler(seed=0)
    set_scheduler(sched)
    run_task = asyncio.ensure_future(sched.run_async())
    server = ChaosCommitServer(sched, engine_mode="oracle", port=port)
    try:
        await server.start()
        print(f"listening on {server.address}", flush=True)
        while True:
            await asyncio.sleep(3600)
    finally:
        await server.stop()
        sched.shutdown()
        run_task.cancel()
        set_scheduler(None)


# -- crash-stop recovery campaign (fault/recovery.py; --crash) ----------------

@dataclass
class CrashConfig:
    """One seeded crash-restart campaign: a RECOVERABLE commit-server
    child (journal + snapshots + progcache in a durable directory) is
    killed -9 mid-load under background disk faults, supervised back up
    by monitor.Child, and must recover — snapshot + differential journal
    replay + progcache rewarm — inside the blackout budget, then serve
    NEW commits that continue the pre-crash history bit-for-bit."""

    seed: int = 11
    engine_mode: str = "jax"
    #: durable directory (bbox-*.seg + snap-*.snap + progcache/);
    #: None = a per-campaign tempdir. Re-runs wipe the journal and
    #: snapshots (versions restart at 0) but KEEP progcache/ on purpose:
    #: rewarm-from-cache is the steady state the budget is sized for.
    datadir: Optional[str] = None
    warm_s: float = 3.0       #: pre-kill serving phase (seeds snapshots)
    post_s: float = 1.5       #: post-recovery serving phase
    #: extra bounded wait for the FIRST post-restart commit before the
    #: post_s window starts counting: the load client's reconnect
    #: backoff after the kill (or the first commit faulting in a program
    #: the rewarm's used-only set skipped) can otherwise eat a fixed
    #: window whole and fail the serving SLO on a healthy node
    post_grace_s: float = 10.0
    rate_tps: float = 120.0
    #: None = the resolver_recovery_budget_ms knob
    budget_ms: Optional[float] = None
    #: per-durable-write disk-fault probability: fsync stalls on the
    #: journal (lossless, so the parity proof holds), torn tails on
    #: snapshots (recovery falls back), rot/ENOSPC on the progcache
    #: (poisoned entries quarantine to a compile)
    disk_prob: float = 0.05
    child_backoff_s: float = 0.3
    #: first-boot serve deadline: a cold device-backed child AOT-compiles
    #: its ladder before listening (restarts rewarm from the progcache)
    boot_timeout_s: float = 240.0

    def resolved_budget_ms(self) -> float:
        base = (float(SERVER_KNOBS.resolver_recovery_budget_ms)
                if self.budget_ms is None else float(self.budget_ms))
        if self.engine_mode not in ("oracle",):
            # device-backed replay re-resolves the suffix through the
            # CPU-emulated device path — same rationale as the p99
            # budget's device-mode factor
            base *= NemesisConfig.DEVICE_MODE_BUDGET_FACTOR
        return base


def crash_config(seed: int, engine_mode: str = "jax", **kw) -> CrashConfig:
    """The `make chaos-crash` campaign point for (seed, engine_mode)."""
    if engine_mode == "device_loop":
        kw.setdefault("warm_s", 5.0)
    return CrashConfig(seed=seed, engine_mode=engine_mode, **kw)


def _crash_child_argv(port: int, datadir: str, engine_mode: str,
                      seed: int, disk_prob: float) -> List[str]:
    code = ("import sys; sys.path.insert(0, %r); "
            "from foundationdb_tpu.real.nemesis import main; "
            "sys.exit(main(['--serve-recover', '%d', '--datadir', %r, "
            "'--child-engine', %r, '--recovery-seed', '%d', "
            "'--disk-prob', '%s']))"
            % (REPO_ROOT, port, datadir, engine_mode, seed, disk_prob))
    return [sys.executable, "-c", code]


async def _child_rpc(port: int, token: str, timeout_s: float = 1.5):
    """One status/span RPC at a (possibly dead) child; None on any
    transport or typed failure — the restart poll's probe."""
    net = RealNetwork(name="crash-prober")
    try:
        return await net.request(
            "prober", Endpoint(f"127.0.0.1:{port}", token), None,
            timeout=timeout_s)
    except (error.FDBError, ConnectionError, OSError):
        return None
    finally:
        net.close()


async def _serve_recoverable(port: int, datadir: str, engine_mode: str,
                             seed: int, disk_prob: float) -> None:
    """The --crash campaign's child: a ChaosCommitServer that RECOVERS
    before it serves. Every boot replays the durable directory — newest
    readable snapshot, then the journal's batch suffix at original
    versions — through fault/recovery, restores the version clock past
    everything recovered, then serves with the journal continuing in
    place (fresh=False) and fsync_interval=1: an acked batch is durable
    before its verdict leaves the process, the crash-window contract
    (docs/observability.md) the parent's parity replay relies on."""
    from ..fault import recovery
    from ..fault.inject import DiskFaultRates
    from ..core.trace import set_process_name, set_span_collection
    from ..sim.loop import TaskPriority, set_scheduler
    from .chaos import DiskNemesis
    from .runtime import RealScheduler

    set_span_collection(True)
    proc = f"crash-server:{port}"
    set_process_name(proc)
    disk = None
    if disk_prob > 0:
        p = float(disk_prob)
        disk = DiskNemesis(
            seed, rates=DiskFaultRates(stall=p, stall_ms=5.0),
            surface_rates={
                "snapshot": DiskFaultRates(stall=p, stall_ms=5.0, torn=p),
                "progcache": DiskFaultRates(enospc=p / 2, rot=p / 2),
            })
    blackbox.install(blackbox.BlackboxJournal(
        datadir, proc=proc, fresh=False, fsync_interval=1, disk=disk))
    progcache.install(progcache.ProgramCache(
        os.path.join(datadir, "progcache"), disk=disk))
    sched = RealScheduler(seed=seed)
    set_scheduler(sched)
    run_task = asyncio.ensure_future(sched.run_async())
    server = ChaosCommitServer(sched, engine_mode=engine_mode, port=port)
    server.disk_nemesis = disk
    tracker = recovery.RecoveryTracker(name=f"crash{port}")
    server.recovery_tracker = tracker
    # recover() resolves replayed batches through the supervised engine,
    # whose sim-loop futures can only be awaited from a task on the
    # cooperative scheduler — bridge the result back to asyncio
    done: asyncio.Future = asyncio.get_event_loop().create_future()

    async def _do_recover() -> None:
        try:
            r = await recovery.recover(server.engine, datadir,
                                       tracker=tracker, proc=proc)
            done.set_result(r)
        except Exception as e:  # pragma: no cover - surfaced to boot log
            done.set_exception(e)

    sched.spawn(_do_recover(), TaskPriority.PROXY_COMMIT_BATCHER,
                name="recover")
    res = await done
    server.last_recovery = res.as_dict()
    server._version = server._committed = max(0, int(res.recovered_version))
    if res.mode == recovery.MODE_COLD and engine_mode != "oracle":
        # first boot: AOT-compile the ladder — and thereby seed the
        # progcache — OFF the serving path; restarts rewarm during replay
        server.warmup()
    server.snapshot_mgr = recovery.SnapshotManager(datadir, disk=disk,
                                                   proc=proc)
    try:
        await server.start()
        print(f"listening on {server.address} recovered={res.mode} "
              f"v={res.recovered_version}", flush=True)
        while True:
            await asyncio.sleep(3600)
    finally:
        await server.stop()
        sched.shutdown()
        run_task.cancel()
        set_scheduler(None)


async def _crash_load(port: int, rng, rate_tps: float,
                      stats: Dict[str, int], vcache: List[int],
                      net: RealNetwork, stop: List[bool]) -> None:
    """Open-loop commit stream at the recoverable child: mixed
    read/write conflict ranges over a small hot keyspace, version cache
    refreshed off the status endpoint on too-old. Runs THROUGH the kill
    window — the dead stretch shows up as transport errors, exactly the
    client view of the blackout."""
    ep = Endpoint(f"127.0.0.1:{port}", COMMIT_TOKEN)
    sep = Endpoint(f"127.0.0.1:{port}", STATUS_TOKEN)
    interval = 1.0 / max(rate_tps, 1.0)
    while not stop[0]:
        ks = [b"ck%04d" % rng.random_int(0, 256) for _ in range(3)]
        body = ("crash", (ks[0],), tuple(ks[1:]), vcache[0])
        try:
            v = await net.request("crash-client", ep, body, timeout=1.0)
            vcache[0] = max(vcache[0], int(v))
            stats["committed"] = stats.get("committed", 0) + 1
        except error.FDBError as e:
            stats[e.name] = stats.get(e.name, 0) + 1
            if e.name == "transaction_too_old":
                try:
                    st = await net.request("crash-client", sep, None,
                                           timeout=1.0)
                    vcache[0] = max(vcache[0],
                                    int(st["committed_version"]))
                except (error.FDBError, ConnectionError, OSError):
                    pass
        except (ConnectionError, OSError):
            stats["transport_errors"] = stats.get("transport_errors", 0) + 1
        await asyncio.sleep(interval)


def replay_events_parity(events) -> Tuple[int, int]:
    """Replay EVERY batch the child's durable journal retained — both
    boots, across the crash — through a clean serial oracle. With the
    journal surface lossless (stall-only faults, fsync_interval=1) the
    retained stream is exactly what the server acked, so the recovered
    engine's post-restart verdicts must CONTINUE the pre-crash history
    bit-for-bit. Returns (batches checked, mismatches)."""
    from ..ops.oracle import OracleConflictEngine

    clean = OracleConflictEngine()
    checked = mismatches = 0
    for e in events:
        if e.kind != "batch":
            continue
        p = e.payload
        want = clean.resolve(list(p.txns), int(p.version),
                             int(p.new_oldest))
        checked += 1
        if [int(x) for x in want] != [int(x) for x in p.verdicts]:
            mismatches += 1
    return checked, mismatches


async def _crash_campaign(cfg: CrashConfig) -> dict:
    from ..core.rng import DeterministicRandom
    from .cluster import free_ports
    from .monitor import Child, poll_children

    telemetry.reset()
    datadir = cfg.datadir or os.path.join(
        tempfile.mkdtemp(prefix="fdb_tpu_crash_"), "node0")
    os.makedirs(datadir, exist_ok=True)
    # deterministic re-run: drop the previous run's journal + snapshots
    # (versions restart at 0) but KEEP progcache/ — the bench's
    # rewarm-from-cache point measures exactly this surviving directory
    for n in os.listdir(datadir):
        if n.startswith(("bbox-", "snap-")):
            try:
                os.remove(os.path.join(datadir, n))
            except OSError:
                pass
    (port,) = free_ports(1)
    log_dir = os.path.join(datadir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    rng = DeterministicRandom(cfg.seed * 7919 + 17)
    report: dict = {"engine_mode": cfg.engine_mode, "seed": cfg.seed,
                    "datadir": datadir,
                    "budget_ms": cfg.resolved_budget_ms(),
                    "child_up": False, "child_restarts": 0,
                    "child_pingable_after": False}
    child = Child("node.crash", _crash_child_argv(
        port, datadir, cfg.engine_mode, cfg.seed, cfg.disk_prob))
    child.backoff = cfg.child_backoff_s
    child.spawn(log_dir)
    net = RealNetwork(name="crash-driver")
    stats: Dict[str, int] = {}
    vcache = [0]
    stop = [False]
    load_task = None
    try:
        deadline = time.monotonic() + cfg.boot_timeout_s
        while time.monotonic() < deadline:
            if await _child_rpc(port, STATUS_TOKEN) is not None:
                report["child_up"] = True
                break
            await asyncio.sleep(0.2)
        if not report["child_up"]:
            return report
        load_task = asyncio.ensure_future(_crash_load(
            port, rng, cfg.rate_tps, stats, vcache, net, stop))
        # pre-kill serving phase: commits flow, snapshots cadence out
        await asyncio.sleep(cfg.warm_s)
        st = await _child_rpc(port, STATUS_TOKEN) or {}
        report["committed_before_kill"] = int(
            st.get("committed_version", 0))
        report["snapshots_before_kill"] = dict(st.get("snapshots") or {})
        telemetry.hub().chaos_event("process_kill", port=port)
        t_kill = time.monotonic()
        child.proc.kill()
        # supervise it back up (backoff + crash counter, real/monitor.py);
        # the restarted child RECOVERS before it listens, so the first
        # successful status is already recovered + serving
        st2 = None
        deadline = time.monotonic() + cfg.boot_timeout_s
        while time.monotonic() < deadline:
            poll_children([child], log_dir)
            if child.restarts >= 1:
                st2 = await _child_rpc(port, STATUS_TOKEN)
                if st2 is not None:
                    break
            await asyncio.sleep(0.1)
        report["child_restarts"] = child.restarts
        report["restart_serve_s"] = round(time.monotonic() - t_kill, 3)
        if st2 is None:
            return report
        telemetry.hub().chaos_event("process_restart", port=port)
        report["child_pingable_after"] = True
        report["recovery"] = st2.get("recovery")
        # post-recovery serving phase: the recovered node must take NEW
        # traffic past everything it recovered
        vcache[0] = max(vcache[0], int(st2.get("committed_version", 0)))
        committed_at_restart = stats.get("committed", 0)
        # evidence-driven post window: wait (bounded) for the first NEW
        # commit to land, then give the load the full post_s to run —
        # the SLO is "the recovered node serves", not "it served within
        # an arbitrary fixed sleep of the restart"
        grace = time.monotonic() + cfg.post_grace_s
        while (stats.get("committed", 0) == committed_at_restart
               and time.monotonic() < grace):
            await asyncio.sleep(0.1)
        await asyncio.sleep(cfg.post_s)
        stop[0] = True
        await load_task
        load_task = None
        st3 = await _child_rpc(port, STATUS_TOKEN) or {}
        report["committed_after"] = int(st3.get("committed_version", 0))
        report["committed_post_restart"] = (stats.get("committed", 0)
                                            - committed_at_restart)
        report["snapshots"] = dict(st3.get("snapshots") or {})
        report["blackbox"] = st3.get("blackbox")
        report["disk"] = st3.get("disk")
        report["progcache"] = st3.get("progcache")
        # span-verified blackout: the restarted process's OWN span ring,
        # fetched over RPC — independent of the recovery code's clocks
        spans = await _child_rpc(port, SPANS_TOKEN)
        report["recovery_span_blackouts_ms"] = [
            r.get("blackout_ms") for r in (spans or {}).get("spans", ())
            if r.get("Name") == "recovery.blackout"
            and r.get("blackout_ms") is not None]
    finally:
        stop[0] = True
        if load_task is not None:
            try:
                await load_task
            except Exception:
                pass
        child.stop()
        net.close()
    report["load"] = dict(stats)
    # the durable copy of the arc + bit-parity through a clean oracle
    events = blackbox.read_journal(datadir)
    report["recovery_events"] = [dict(vars(e.payload)) for e in events
                                 if e.kind == "recovery"]
    report["snapshot_events"] = sum(1 for e in events
                                    if e.kind == "snapshot")
    checked, mismatches = replay_events_parity(events)
    report["parity_checked"] = checked
    report["parity_mismatches"] = mismatches
    report["chaos_counts"] = telemetry.hub().chaos_counts()
    return report


def run_crash_campaign(cfg: CrashConfig) -> dict:
    t0 = time.monotonic()
    rep = asyncio.run(_crash_campaign(cfg))
    rep["wall_s"] = round(time.monotonic() - t0, 3)
    return rep


def assert_crash_slos(report: dict, cfg: CrashConfig) -> None:
    """Machine-assert the crash-restart contract — never by eyeball."""
    ctx = f"(engine={cfg.engine_mode} seed={cfg.seed})"
    assert report.get("child_up"), f"child never served {ctx}"
    assert report.get("child_restarts", 0) >= 1, \
        f"child was not supervised back up {ctx}"
    assert report.get("child_pingable_after"), \
        f"restarted child never answered status {ctx}"
    assert report.get("committed_after", 0) > \
        report.get("committed_before_kill", 0), \
        f"recovered child served no new commits {ctx}"
    assert report.get("committed_post_restart", 0) > 0, \
        f"no client commit succeeded post-recovery {ctx}"
    rec = report.get("recovery") or {}
    assert not rec.get("error"), \
        f"recovery errored: {rec.get('error')} {ctx}"
    assert rec.get("mode") == "complete" and rec.get("coverage_ok"), \
        f"recovery not provably complete: {rec} {ctx}"
    assert rec.get("verdict_mismatches", 1) == 0, \
        f"recovery replay diverged: {rec} {ctx}"
    assert (rec.get("snapshot_version", -1) >= 0
            or rec.get("replayed_batches", 0) > 0), \
        f"recovery recovered nothing durable: {rec} {ctx}"
    budget = cfg.resolved_budget_ms()
    assert rec.get("blackout_ms", budget + 1) <= budget, \
        (f"recovery blackout {rec.get('blackout_ms')}ms "
         f"> budget {budget}ms {ctx}")
    blk = report.get("recovery_span_blackouts_ms") or []
    assert blk, f"no recovery.blackout span fetched from the child {ctx}"
    assert max(blk) <= budget, \
        f"span-verified blackout {max(blk)}ms > budget {budget}ms {ctx}"
    assert report.get("snapshot_events", 0) >= 1, \
        f"no snapshot ever cadenced out {ctx}"
    assert report.get("parity_checked", 0) > 0, \
        f"no journal batches to replay {ctx}"
    assert report.get("parity_mismatches", 0) == 0, \
        (f"{report.get('parity_mismatches')} parity mismatches across "
         f"the crash {ctx}")
    # disk incidents explained: the journal surface must have stayed
    # LOSSLESS (the parity proof's precondition — stall-only faults),
    # and every injected fault is inventoried in the report
    bb = report.get("blackbox") or {}
    assert int(bb.get("shed_events", 0)) == 0 \
        and not bb.get("durability_gap"), \
        f"journal lost records under disk faults: {bb} {ctx}"


# -- CLI ----------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="wall-clock chaos campaign with machine-asserted SLOs")
    ap.add_argument("--seeds", type=int, default=2)
    ap.add_argument("--base-seed", type=int, default=11)
    ap.add_argument("--engine-modes", default="jax,device_loop",
                    help="comma list of oracle|jax|device_loop|mesh")
    ap.add_argument("--duration", type=float, default=None,
                    help="campaign seconds (default 4.0; --drift defaults "
                         "6.0 oracle / 10.0 device-backed)")
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="explicit p99 budget; default is the knob product "
                         "resolver_p99_budget_ms x real_chaos_budget_factor "
                         "(the wall-clock serving point — see the factor "
                         "knob's rationale in core/knobs.py)")
    ap.add_argument("--sweep", action="store_true",
                    help="also run the served_under_chaos Zipf sweep")
    ap.add_argument("--json", default=None, help="write reports to this file")
    ap.add_argument("--trace-dir", default=None,
                    help="write each campaign's tail-sampled cross-process "
                         "Chrome trace JSON into this directory "
                         "(chrome://tracing / Perfetto loadable)")
    ap.add_argument("--blackbox-dir", default=None,
                    help="write each campaign's durable black-box journal "
                         "into a per-campaign subdirectory of this path "
                         "(core/blackbox.py; `cli explain <version> "
                         "REPORT.json` narrates any resolved version "
                         "post-hoc, `cli blackbox replay` diffs a window "
                         "against the serial oracle)")
    ap.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="run a traced commit server solo on PORT "
                         "(the trace-smoke child process) and never return")
    ap.add_argument("--crash", action="store_true",
                    help="run the crash-restart campaign instead of the "
                         "fault campaign: a recoverable child (journal + "
                         "snapshots + progcache) killed -9 mid-load under "
                         "disk faults, supervised back up, and required "
                         "to recover inside resolver_recovery_budget_ms "
                         "with bit-identical replay parity across the "
                         "crash (docs/fault_tolerance.md)")
    ap.add_argument("--serve-recover", type=int, default=None,
                    metavar="PORT",
                    help="run the --crash campaign's RECOVERABLE commit "
                         "server solo on PORT (recovers --datadir before "
                         "listening) and never return")
    ap.add_argument("--datadir", default=None,
                    help="--serve-recover / --crash durable directory")
    ap.add_argument("--child-engine", default="jax",
                    help="--serve-recover engine mode")
    ap.add_argument("--recovery-seed", type=int, default=11,
                    help="--serve-recover nemesis seed")
    ap.add_argument("--disk-prob", type=float, default=0.05,
                    help="--serve-recover per-write disk-fault "
                         "probability")
    ap.add_argument("--drift", action="store_true",
                    help="run the diurnal drift campaign instead of the "
                         "fault campaign: elastic resolver group + "
                         "heat-driven online resharding under a drifting "
                         "Zipf fleet; assert_slos additionally requires "
                         ">= 2 executed reshards with every blackout "
                         "inside reshard_blackout_budget_ms "
                         "(docs/elasticity.md)")
    ap.add_argument("--watchdog", action="store_true",
                    help="attach the cluster watchdog (core/watchdog.py): "
                         "live burn-rate/anomaly alerts during the "
                         "campaign, incident timelines in the report "
                         "(`cli incidents REPORT.json`), and assert_slos "
                         "additionally requires every firing incident "
                         "explained by an injected fault window")
    args = ap.parse_args(argv)
    if args.serve is not None:
        try:
            asyncio.run(_serve_commit(args.serve))
        except KeyboardInterrupt:
            pass
        return 0

    if args.serve_recover is not None:
        # NO jax persistent compilation cache here, deliberately: an
        # executable that jax itself deserialized from its cache
        # re-serializes as a non-self-contained artifact ("Symbols not
        # found" on the next process's deserialize_and_load), which
        # would silently poison every progcache entry the child writes.
        # The on-disk progcache IS this child's cross-restart cache.
        if not args.datadir:
            print("--serve-recover requires --datadir", file=sys.stderr)
            return 2
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            asyncio.run(_serve_recoverable(
                args.serve_recover, args.datadir, args.child_engine,
                args.recovery_seed, args.disk_prob))
        except KeyboardInterrupt:
            pass
        return 0

    # compile-cache like tests/conftest.py: repeated campaigns must not
    # repay the kernel compile (solo-CPU friendliness)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.expanduser("~"), ".cache", "fdb_tpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    modes = [m for m in args.engine_modes.split(",") if m]
    reports, failures = [], 0
    for mode in modes:
        # device-backed modes run longer: their fault windows (rewarm is
        # ~10 ms per shadow batch on CPU) eat more of the run, and the SLO
        # needs enough outside-window samples for a meaningful p99
        base_duration = 4.0 if args.duration is None else args.duration
        duration = (base_duration if mode == "oracle"
                    else max(base_duration, 8.0))
        for i in range(args.seeds):
            seed = args.base_seed + i
            if args.crash:
                ccfg = crash_config(
                    seed, engine_mode=mode, budget_ms=args.budget_ms,
                    datadir=(os.path.join(args.blackbox_dir,
                                          f"crash_{mode}_s{seed}")
                             if args.blackbox_dir else None),
                    disk_prob=args.disk_prob)
                print(f"crash campaign: engine={mode} seed={seed} ...",
                      flush=True)
                rep_c = run_crash_campaign(ccfg)
                reports.append(rep_c)
                try:
                    assert_crash_slos(rep_c, ccfg)
                    recd = rep_c.get("recovery") or {}
                    blk = rep_c.get("recovery_span_blackouts_ms") or [0.0]
                    print(f"  OK  blackout={recd.get('blackout_ms')}ms "
                          f"(span {max(blk):.1f}ms, budget "
                          f"{ccfg.resolved_budget_ms():.0f}ms) "
                          f"mode={recd.get('mode')} "
                          f"replayed={recd.get('replayed_batches')} "
                          f"snap_v={recd.get('snapshot_version')} "
                          f"progcache_hits={recd.get('progcache_hits')} "
                          f"parity={rep_c.get('parity_checked')} "
                          f"restarts={rep_c.get('child_restarts')}",
                          flush=True)
                except AssertionError as e:
                    failures += 1
                    print(f"  SLO FAILED: {e}", file=sys.stderr,
                          flush=True)
                continue
            trace_path = (os.path.join(args.trace_dir,
                                       f"trace_{mode}_s{seed}.json")
                          if args.trace_dir else None)
            bb_dir = (os.path.join(args.blackbox_dir, f"{mode}_s{seed}")
                      if args.blackbox_dir else None)
            if args.drift:
                cfg = drift_config(seed, engine_mode=mode,
                                   duration_s=args.duration,
                                   budget_ms=args.budget_ms,
                                   trace_export=trace_path,
                                   blackbox_dir=bb_dir,
                                   watchdog=True if args.watchdog else None)
            else:
                cfg = NemesisConfig(seed=seed, engine_mode=mode,
                                    duration_s=duration,
                                    budget_ms=args.budget_ms,
                                    trace_export=trace_path,
                                    blackbox_dir=bb_dir,
                                    watchdog=True if args.watchdog else None)
            print(f"campaign: engine={mode} seed={seed}"
                  + (" [drift]" if args.drift else "") + " ...", flush=True)
            rep = run_campaign(cfg)
            reports.append(rep.as_dict())
            if rep.trace_file:
                # schema-check every export right here: a campaign whose
                # trace JSON would not load is a failed campaign
                with open(rep.trace_file) as f:
                    n_events = trace_export.validate_chrome_trace(json.load(f))
                tr = rep.traces or {}
                print(f"  traces -> {rep.trace_file} ({n_events} events, "
                      f"{tr.get('retained')} retained of "
                      f"{tr.get('n_waterfalls')} waterfalls)", flush=True)
            try:
                assert_slos(rep, cfg)
                rs = rep.reshard or {}
                print(f"  OK  p99_outside={rep.p99_outside_ms:.3f}ms "
                      f"(budget {cfg.resolved_budget_ms()}ms, "
                      f"n={rep.n_outside}) parity={rep.parity_checked} "
                      f"failovers={rep.engine_stats.get('failovers')} "
                      f"swap_backs={rep.engine_stats.get('swap_backs')} "
                      f"child_restarts={rep.child_restarts}"
                      + (f" reshards={rs.get('executed')} "
                         f"(blackout_max={rs.get('blackout_ms_max')}ms, "
                         f"epoch={rs.get('epoch')})"
                         if rep.reshard is not None else "")
                      + (f" incidents={len(rep.incidents)} (all explained)"
                         if rep.incidents is not None else ""), flush=True)
            except AssertionError as e:
                failures += 1
                print(f"  SLO FAILED: {e}", file=sys.stderr, flush=True)
    # aggregate across ALL campaigns: each run resets the telemetry hub,
    # so the live chaos_status_lines() view only covers the last one —
    # the run log must report the whole invocation's injected inventory
    totals: Dict[str, int] = {}
    for rep_d in reports:
        for kind, n in (rep_d.get("chaos_counts") or {}).items():
            totals[kind] = totals.get(kind, 0) + n
    print(f"nemesis event counts across {len(reports)} campaign(s):")
    for kind in sorted(totals):
        print(f"  {kind:<18} {totals[kind]}")
    out = {"campaigns": reports}
    if args.sweep:
        print("served_under_chaos sweep ...", flush=True)
        sweep = run_served_under_chaos(budget_ms=args.budget_ms)
        out["served_under_chaos"] = sweep
        print(json.dumps(sweep["users_served_per_chip"]))
        for row in sweep["sweep"]:
            print(f"  s={row['s']:<4} admission={str(row['admission']):<5} "
                  f"p99={row['p99_ms']:>9.3f}ms in_budget={row['in_budget']} "
                  f"throttled={row['throttled_frac']:.0%} "
                  f"aborts={row['abort_frac']:.0%}", flush=True)
        ok_ctrl = all(r["in_budget"] for r in sweep["sweep"] if r["admission"])
        bad_unctrl = all(not r["in_budget"]
                         for r in sweep["sweep"] if not r["admission"])
        if not (ok_ctrl and bad_unctrl):
            failures += 1
            print("SWEEP FAILED: admission must hold p99 in budget while "
                  "uncontrolled runs exceed it", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2, default=str)
        print(f"reports -> {args.json}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
