"""Open-loop Zipfian workload fleet for the wall-clock cluster.

The bench's latency harness drives the SIM cluster with a Poisson stream;
nothing modelled realistic traffic against the real transport (ROADMAP
item 4). This fleet does: multi-tenant open-loop streams over real
sockets, each tenant an independent Poisson arrival process at its own
target txn/s over its own Zipf(s)-skewed hot-key pool. Open-loop is the
honest shape (Harmonia-style offered load): a txn is submitted at its
arrival time regardless of outstanding ones, so server-side queueing
shows up as client latency, never as politely reduced load. Skew is the
point — Proust's design-space analysis (PAPERS.md) shows optimistic
schemes bite under hot-key contention, so robustness is proven at
s ∈ {0, 0.9, 1.2}, not under uniform smoke traffic.

The fleet is transport-agnostic: it drives a `submit(spec, reads,
writes)` coroutine (real/nemesis.py supplies one over ChaosTransport) and
records (t_submit, latency_s, ok, version, err_name) per tenant — the
shape `pipeline/latency_harness.percentile_outside_windows` asserts SLOs
over (docs/real_cluster.md).
"""
from __future__ import annotations

import asyncio
import bisect
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..core.rng import DeterministicRandom

#: ack error names that are honest, full-path verdicts (their latency
#: belongs in the SLO population, like the sim harness's conflict acks)
VERDICT_ERRORS = ("not_committed", "transaction_too_old")
#: fast typed rejection from per-tenant admission control — NOT a latency
#: sample (the tenant was told to back off in microseconds); reported as
#: rejected_frac instead
THROTTLE_ERROR = "transaction_throttled"


def zipf_cdf(n_keys: int, s: float) -> List[float]:
    """Cumulative Zipf(s) distribution over ranks 1..n (s=0 -> uniform)."""
    weights = [1.0 / (k ** s) for k in range(1, n_keys + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    cdf[-1] = 1.0
    return cdf


class ZipfKeySampler:
    """Seeded rank-Zipf sampler: rank 0 is the hottest key. Inverse-CDF
    via bisect — O(log n) per draw, no numpy in the hot path.

    `drift` rotates the rank->key mapping over time: at elapsed time t
    the hottest RANK lands on key index `int(drift * t) % n_keys`, so the
    hot range sweeps the tenant's keyspace — the diurnal-shift model the
    drift campaign (real/nemesis.py) reshards under. drift=0 keeps the
    classic static mapping."""

    def __init__(self, n_keys: int, s: float, rng: DeterministicRandom,
                 drift: float = 0.0):
        self.n_keys = n_keys
        self.s = s
        self.rng = rng
        self.drift = float(drift)
        self._cdf = zipf_cdf(n_keys, s)

    def sample(self, t_rel: float = 0.0) -> int:
        rank = bisect.bisect_left(self._cdf, self.rng.random01())
        if self.drift:
            rank = (rank + int(self.drift * t_rel)) % self.n_keys
        return rank


@dataclass
class TenantSpec:
    """One tenant's stream: open-loop Poisson at `target_tps` over a
    `n_keys` pool with Zipf skew `s` (0 = uniform)."""

    name: str
    target_tps: float
    s: float = 0.0
    n_keys: int = 512
    reads_per_txn: int = 2
    writes_per_txn: int = 2
    key_prefix: bytes = b""
    #: hot-range drift in key indices per second (0 = stationary): the
    #: Zipf head sweeps the pool at this speed, so load concentration
    #: MOVES through the keyspace over the campaign
    drift_keys_per_s: float = 0.0

    def prefix(self) -> bytes:
        return self.key_prefix or self.name.encode()


@dataclass
class FleetReport:
    """What the fleet observed, per tenant and overall."""

    #: tenant -> [(t_submit, latency_s, ok, version, err_name)]
    records: Dict[str, List[Tuple]] = field(default_factory=dict)
    #: tenant -> error name -> count (transport errors, throttles, ...)
    errors: Dict[str, Dict[str, int]] = field(default_factory=dict)
    offered: Dict[str, int] = field(default_factory=dict)
    t_start: float = 0.0
    t_end: float = 0.0

    def ack_records(self, tenant: Optional[str] = None) -> List[Tuple]:
        """Latency-population records (committed + verdict acks): the SLO
        sample set, as (t0, lat, ok, version) 4-tuples."""
        out = []
        for name, recs in self.records.items():
            if tenant is not None and name != tenant:
                continue
            for t0, lat, ok, version, err in recs:
                if ok or err in VERDICT_ERRORS:
                    out.append((t0, lat, ok, version))
        out.sort(key=lambda r: r[0])
        return out

    def counts(self, tenant: Optional[str] = None) -> Dict[str, int]:
        sel = [(n, r) for n, r in self.records.items()
               if tenant is None or n == tenant]
        committed = sum(1 for _n, recs in sel for r in recs if r[2])
        conflicted = sum(1 for _n, recs in sel for r in recs
                         if not r[2] and r[4] in VERDICT_ERRORS)
        throttled = sum(e.get(THROTTLE_ERROR, 0)
                        for n, e in self.errors.items()
                        if tenant is None or n == tenant)
        transport = sum(c for n, e in self.errors.items()
                        if tenant is None or n == tenant
                        for k, c in e.items()
                        if k not in VERDICT_ERRORS + (THROTTLE_ERROR,))
        offered = sum(c for n, c in self.offered.items()
                      if tenant is None or n == tenant)
        return {"offered": offered, "committed": committed,
                "conflicted": conflicted, "throttled": throttled,
                "transport_errors": transport}

    def sustained_tps(self, tenant: Optional[str] = None) -> float:
        acks = self.ack_records(tenant)
        if len(acks) < 2:
            return 0.0
        span = acks[-1][0] - acks[0][0]
        return len(acks) / max(span, 1e-9)


class WorkloadFleet:
    """Drive every tenant's open-loop stream concurrently on asyncio."""

    def __init__(self, tenants: List[TenantSpec],
                 submit: Callable, seed: int = 0,
                 duration_s: float = 5.0,
                 max_outstanding: int = 2048,
                 report: Optional[FleetReport] = None):
        self.tenants = tenants
        self.submit = submit
        self.seed = seed
        self.duration_s = duration_s
        #: open-loop guard rail: past this many outstanding submissions a
        #: tenant sheds locally (records a client_overload error) instead
        #: of growing the task set without bound while the server is
        #: partitioned away — the open-loop contract holds far beyond any
        #: SLO-passing regime, this only bounds memory in the failed one
        self.max_outstanding = max_outstanding
        #: pass an existing report to APPEND a phase (the campaign's
        #: post-recovery cooldown records into the same population)
        self.report = report if report is not None else FleetReport()
        self._outstanding: Dict[str, int] = {}
        self._phase_start = 0.0

    async def _one_txn(self, spec: TenantSpec, sampler: ZipfKeySampler) -> None:
        from ..core import error as _error

        rep = self.report
        pfx = spec.prefix()
        t_rel = time.monotonic() - (rep.t_start or self._phase_start)
        reads = [b"%s/%06d" % (pfx, sampler.sample(t_rel))
                 for _ in range(spec.reads_per_txn)]
        writes = [b"%s/%06d" % (pfx, sampler.sample(t_rel))
                  for _ in range(spec.writes_per_txn)]
        t0 = time.monotonic()
        ok, version, err = False, None, None
        try:
            version = await self.submit(spec, reads, writes)
            ok = True
        except _error.FDBError as e:
            err = e.name
        except (ConnectionError, OSError) as e:
            err = type(e).__name__
        lat = time.monotonic() - t0
        if err is not None and err not in VERDICT_ERRORS:
            rep.errors[spec.name][err] = rep.errors[spec.name].get(err, 0) + 1
        if ok or err in VERDICT_ERRORS:
            rep.records[spec.name].append((t0, lat, ok, version, err))
        self._outstanding[spec.name] -= 1

    async def _tenant_stream(self, spec: TenantSpec,
                             rng: DeterministicRandom) -> None:
        sampler = ZipfKeySampler(spec.n_keys, spec.s,
                                 DeterministicRandom(rng.random_int(0, 2**31 - 1)),
                                 drift=spec.drift_keys_per_s)
        lam = max(spec.target_tps, 1e-3)
        t_end = self._phase_start + self.duration_s
        tasks: set = set()
        while time.monotonic() < t_end:
            await asyncio.sleep(-math.log(max(rng.random01(), 1e-12)) / lam)
            self.report.offered[spec.name] = \
                self.report.offered.get(spec.name, 0) + 1
            if self._outstanding[spec.name] >= self.max_outstanding:
                e = self.report.errors[spec.name]
                e["client_overload"] = e.get("client_overload", 0) + 1
                continue
            self._outstanding[spec.name] += 1
            t = asyncio.ensure_future(self._one_txn(spec, sampler))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
        if tasks:
            await asyncio.wait(tasks, timeout=10.0)

    async def run(self) -> FleetReport:
        rng = DeterministicRandom(self.seed)
        rep = self.report
        if not rep.t_start:
            rep.t_start = time.monotonic()
        self._phase_start = time.monotonic()
        for spec in self.tenants:
            rep.records.setdefault(spec.name, [])
            rep.errors.setdefault(spec.name, {})
            rep.offered.setdefault(spec.name, 0)
            self._outstanding[spec.name] = 0
        streams = [
            self._tenant_stream(spec,
                                DeterministicRandom(rng.random_int(0, 2**31 - 1)))
            for spec in self.tenants
        ]
        await asyncio.gather(*streams)
        rep.t_end = time.monotonic()
        return rep
