"""Open-loop Zipfian workload fleet for the wall-clock cluster.

The bench's latency harness drives the SIM cluster with a Poisson stream;
nothing modelled realistic traffic against the real transport (ROADMAP
item 4). This fleet does: multi-tenant open-loop streams over real
sockets, each tenant an independent Poisson arrival process at its own
target txn/s over its own Zipf(s)-skewed hot-key pool. Open-loop is the
honest shape (Harmonia-style offered load): a txn is submitted at its
arrival time regardless of outstanding ones, so server-side queueing
shows up as client latency, never as politely reduced load. Skew is the
point — Proust's design-space analysis (PAPERS.md) shows optimistic
schemes bite under hot-key contention, so robustness is proven at
s ∈ {0, 0.9, 1.2}, not under uniform smoke traffic.

The fleet is transport-agnostic: it drives a `submit(spec, reads,
writes)` coroutine (real/nemesis.py supplies one over ChaosTransport) and
records (t_submit, latency_s, ok, version, err_name) per tenant — the
shape `pipeline/latency_harness.percentile_outside_windows` asserts SLOs
over (docs/real_cluster.md).
"""
from __future__ import annotations

import asyncio
import bisect
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..core.rng import DeterministicRandom

#: ack error names that are honest, full-path verdicts (their latency
#: belongs in the SLO population, like the sim harness's conflict acks)
VERDICT_ERRORS = ("not_committed", "transaction_too_old")
#: txn-shape registry (docs/scenarios.md): how a tenant stream turns
#: sampled key indices into (reads, writes) lists. "zipf" is the classic
#: independent point-read/point-write stream every pre-atlas campaign
#: ran; the rest model the scenario atlas's production access shapes. A
#: write entry is either a point key (bytes) or a (begin, end) RANGE
#: tuple — TTL sweeps clear whole segments in one conflict range.
TXN_SHAPES = ("zipf", "rmw", "fanout", "monotone", "queue", "ttl_cache")
#: fast typed rejection from per-tenant admission control — NOT a latency
#: sample (the tenant was told to back off in microseconds); reported as
#: rejected_frac instead
THROTTLE_ERROR = "transaction_throttled"


def zipf_cdf(n_keys: int, s: float) -> List[float]:
    """Cumulative Zipf(s) distribution over ranks 1..n (s=0 -> uniform)."""
    weights = [1.0 / (k ** s) for k in range(1, n_keys + 1)]
    total = sum(weights)
    cdf, acc = [], 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    cdf[-1] = 1.0
    return cdf


class ZipfKeySampler:
    """Seeded rank-Zipf sampler: rank 0 is the hottest key. Inverse-CDF
    via bisect — O(log n) per draw, no numpy in the hot path.

    `drift` rotates the rank->key mapping over time: at elapsed time t
    the hottest RANK lands on key index `int(drift * t) % n_keys`, so the
    hot range sweeps the tenant's keyspace — the diurnal-shift model the
    drift campaign (real/nemesis.py) reshards under. drift=0 keeps the
    classic static mapping."""

    def __init__(self, n_keys: int, s: float, rng: DeterministicRandom,
                 drift: float = 0.0):
        self.n_keys = n_keys
        self.s = s
        self.rng = rng
        self.drift = float(drift)
        self._cdf = zipf_cdf(n_keys, s)

    def sample(self, t_rel: float = 0.0) -> int:
        rank = bisect.bisect_left(self._cdf, self.rng.random01())
        if self.drift:
            rank = (rank + int(self.drift * t_rel)) % self.n_keys
        return rank


@dataclass
class TenantSpec:
    """One tenant's stream: open-loop Poisson at `target_tps` over a
    `n_keys` pool with Zipf skew `s` (0 = uniform)."""

    name: str
    target_tps: float
    s: float = 0.0
    n_keys: int = 512
    reads_per_txn: int = 2
    writes_per_txn: int = 2
    key_prefix: bytes = b""
    #: hot-range drift in key indices per second (0 = stationary): the
    #: Zipf head sweeps the pool at this speed, so load concentration
    #: MOVES through the keyspace over the campaign
    drift_keys_per_s: float = 0.0
    #: txn shape (one of TXN_SHAPES): how sampled indices become the
    #: commit's reads/writes. "zipf" keeps the pre-atlas stream
    #: byte-identical (same rng draws in the same order).
    shape: str = "zipf"
    #: ttl_cache only: one commit in `ttl_sweep_every` is a TTL sweep —
    #: ONE (begin, end) range delete spanning `ttl_sweep_span` key
    #: indices of the tenant's pool
    ttl_sweep_every: int = 24
    ttl_sweep_span: int = 64

    def prefix(self) -> bytes:
        return self.key_prefix or self.name.encode()


class TxnShaper:
    """Per-stream seeded (reads, writes) generator for one tenant.

    One instance per tenant stream: the monotone/queue shapes carry a
    tail counter, and the op-mix shapes draw from their OWN
    DeterministicRandom so the sampler's Zipf stream stays untouched.
    The "zipf" shape is stateless and never touches `rng` — the fleet
    passes rng=None there so the legacy per-tenant seed stream (and
    therefore every pre-atlas campaign) is byte-identical."""

    def __init__(self, spec: TenantSpec, sampler: ZipfKeySampler,
                 rng: Optional[DeterministicRandom] = None):
        if spec.shape not in TXN_SHAPES:
            raise ValueError(
                f"unknown txn shape {spec.shape!r} (one of {TXN_SHAPES})")
        self.spec = spec
        self.sampler = sampler
        self.rng = rng
        #: monotone/queue tail position (key index of the newest row)
        self.counter = 0

    def build(self, t_rel: float = 0.0) -> Tuple[List, List]:
        spec, sampler = self.spec, self.sampler
        pfx = spec.prefix()

        def key(i: int) -> bytes:
            return b"%s/%06d" % (pfx, max(int(i), 0))

        shape = spec.shape
        if shape == "zipf":
            reads = [key(sampler.sample(t_rel))
                     for _ in range(spec.reads_per_txn)]
            writes = [key(sampler.sample(t_rel))
                      for _ in range(spec.writes_per_txn)]
            return reads, writes
        if shape == "rmw":
            # read-modify-write chain: every written row is read first
            # at the same snapshot (the balance rows of a payment
            # ledger) — the conflict-heavy shape Proust's design-space
            # analysis shows optimistic schemes bite on
            ks, seen = [], set()
            for _ in range(max(spec.writes_per_txn, 1)):
                i = sampler.sample(t_rel)
                if i not in seen:
                    seen.add(i)
                    ks.append(i)
            keys = [key(i) for i in ks]
            return keys, list(keys)
        if shape == "fanout":
            # secondary-index maintenance: one base-row update fans out
            # to index entries under disjoint `.ixN` prefixes — ONE txn
            # whose conflict ranges span multiple key ranges
            base = sampler.sample(t_rel)
            writes = [key(base)] + [
                b"%s.ix%d/%06d" % (pfx, j, sampler.sample(t_rel))
                for j in range(max(spec.writes_per_txn, 1))]
            return [key(base)], writes
        if shape == "monotone":
            # time-series ingest: every commit appends at the tail, so
            # the hottest range is always the NEWEST one — adversarial
            # for static key-range splits (the tail outruns any split
            # chosen from past heat)
            self.counter += 1
            tail = self.counter
            reads = [key(tail - 1 - self.rng.random_int(0, 8))
                     for _ in range(max(spec.reads_per_txn, 1))]
            return reads, [key(tail)]
        if shape == "queue":
            # task queue: producers append at the tail, consumers claim
            # at the head by read-then-write of the same slot — the
            # future commutative-lane showcase (appends commute; claims
            # contend on the head)
            if self.rng.random01() < 0.5:
                self.counter += 1
                return [], [key(self.counter)]
            head = self.counter - self.rng.random_int(0, 15)
            return [key(head)], [key(head)]
        # ttl_cache — session cache: read-mostly point gets with a
        # cadenced TTL sweep: ONE (begin, end) RANGE delete clearing a
        # cold segment of the pool in a single conflict range
        self.counter += 1
        if self.counter % max(spec.ttl_sweep_every, 1) == 0:
            lo = sampler.sample(t_rel)
            return [], [(key(lo), key(lo + max(spec.ttl_sweep_span, 1)))]
        reads = [key(sampler.sample(t_rel))
                 for _ in range(max(spec.reads_per_txn, 1))]
        writes = ([key(sampler.sample(t_rel))]
                  if self.rng.random01() < 0.125 else [])
        return reads, writes


@dataclass
class FleetReport:
    """What the fleet observed, per tenant and overall."""

    #: tenant -> [(t_submit, latency_s, ok, version, err_name)]
    records: Dict[str, List[Tuple]] = field(default_factory=dict)
    #: tenant -> error name -> count (transport errors, throttles, ...)
    errors: Dict[str, Dict[str, int]] = field(default_factory=dict)
    offered: Dict[str, int] = field(default_factory=dict)
    t_start: float = 0.0
    t_end: float = 0.0

    def ack_records(self, tenant: Optional[str] = None) -> List[Tuple]:
        """Latency-population records (committed + verdict acks): the SLO
        sample set, as (t0, lat, ok, version) 4-tuples."""
        out = []
        for name, recs in self.records.items():
            if tenant is not None and name != tenant:
                continue
            for t0, lat, ok, version, err in recs:
                if ok or err in VERDICT_ERRORS:
                    out.append((t0, lat, ok, version))
        out.sort(key=lambda r: r[0])
        return out

    def counts(self, tenant: Optional[str] = None) -> Dict[str, int]:
        sel = [(n, r) for n, r in self.records.items()
               if tenant is None or n == tenant]
        committed = sum(1 for _n, recs in sel for r in recs if r[2])
        conflicted = sum(1 for _n, recs in sel for r in recs
                         if not r[2] and r[4] in VERDICT_ERRORS)
        throttled = sum(e.get(THROTTLE_ERROR, 0)
                        for n, e in self.errors.items()
                        if tenant is None or n == tenant)
        transport = sum(c for n, e in self.errors.items()
                        if tenant is None or n == tenant
                        for k, c in e.items()
                        if k not in VERDICT_ERRORS + (THROTTLE_ERROR,))
        offered = sum(c for n, c in self.offered.items()
                      if tenant is None or n == tenant)
        return {"offered": offered, "committed": committed,
                "conflicted": conflicted, "throttled": throttled,
                "transport_errors": transport}

    def sustained_tps(self, tenant: Optional[str] = None) -> float:
        acks = self.ack_records(tenant)
        if len(acks) < 2:
            return 0.0
        span = acks[-1][0] - acks[0][0]
        return len(acks) / max(span, 1e-9)


class WorkloadFleet:
    """Drive every tenant's open-loop stream concurrently on asyncio."""

    def __init__(self, tenants: List[TenantSpec],
                 submit: Callable, seed: int = 0,
                 duration_s: float = 5.0,
                 max_outstanding: int = 2048,
                 report: Optional[FleetReport] = None):
        self.tenants = tenants
        self.submit = submit
        self.seed = seed
        self.duration_s = duration_s
        #: open-loop guard rail: past this many outstanding submissions a
        #: tenant sheds locally (records a client_overload error) instead
        #: of growing the task set without bound while the server is
        #: partitioned away — the open-loop contract holds far beyond any
        #: SLO-passing regime, this only bounds memory in the failed one
        self.max_outstanding = max_outstanding
        #: pass an existing report to APPEND a phase (the campaign's
        #: post-recovery cooldown records into the same population)
        self.report = report if report is not None else FleetReport()
        self._outstanding: Dict[str, int] = {}
        self._phase_start = 0.0

    async def _one_txn(self, spec: TenantSpec, shaper: TxnShaper) -> None:
        from ..core import error as _error

        rep = self.report
        t_rel = time.monotonic() - (rep.t_start or self._phase_start)
        reads, writes = shaper.build(t_rel)
        t0 = time.monotonic()
        ok, version, err = False, None, None
        try:
            version = await self.submit(spec, reads, writes)
            ok = True
        except _error.FDBError as e:
            err = e.name
        except (ConnectionError, OSError) as e:
            err = type(e).__name__
        lat = time.monotonic() - t0
        if err is not None and err not in VERDICT_ERRORS:
            rep.errors[spec.name][err] = rep.errors[spec.name].get(err, 0) + 1
        if ok or err in VERDICT_ERRORS:
            rep.records[spec.name].append((t0, lat, ok, version, err))
        self._outstanding[spec.name] -= 1

    async def _tenant_stream(self, spec: TenantSpec,
                             rng: DeterministicRandom) -> None:
        sampler = ZipfKeySampler(spec.n_keys, spec.s,
                                 DeterministicRandom(rng.random_int(0, 2**31 - 1)),
                                 drift=spec.drift_keys_per_s)
        # the zipf shape draws NO extra seed: the legacy arrival stream
        # (and every pre-atlas campaign) stays byte-identical
        shape_rng = (DeterministicRandom(rng.random_int(0, 2**31 - 1))
                     if spec.shape != "zipf" else None)
        shaper = TxnShaper(spec, sampler, shape_rng)
        lam = max(spec.target_tps, 1e-3)
        t_end = self._phase_start + self.duration_s
        tasks: set = set()
        while time.monotonic() < t_end:
            await asyncio.sleep(-math.log(max(rng.random01(), 1e-12)) / lam)
            self.report.offered[spec.name] = \
                self.report.offered.get(spec.name, 0) + 1
            if self._outstanding[spec.name] >= self.max_outstanding:
                e = self.report.errors[spec.name]
                e["client_overload"] = e.get("client_overload", 0) + 1
                continue
            self._outstanding[spec.name] += 1
            t = asyncio.ensure_future(self._one_txn(spec, shaper))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
        if tasks:
            await asyncio.wait(tasks, timeout=10.0)

    async def run(self) -> FleetReport:
        rng = DeterministicRandom(self.seed)
        rep = self.report
        if not rep.t_start:
            rep.t_start = time.monotonic()
        self._phase_start = time.monotonic()
        for spec in self.tenants:
            rep.records.setdefault(spec.name, [])
            rep.errors.setdefault(spec.name, {})
            rep.offered.setdefault(spec.name, 0)
            self._outstanding[spec.name] = 0
        streams = [
            self._tenant_stream(spec,
                                DeterministicRandom(rng.random_int(0, 2**31 - 1)))
            for spec in self.tenants
        ]
        await asyncio.gather(*streams)
        rep.t_end = time.monotonic()
        return rep
