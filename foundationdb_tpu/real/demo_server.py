"""A standalone KV node over the real transport — the two-OS-process
demo: run one of these per terminal, point a client at it over TCP.

    python -m foundationdb_tpu.real.demo_server --port 4500

Serves the storage-interface message types (GetValueRequest /
GetKeyValuesRequest) plus set/clear one-ways, all serialized with the
versioned flat wire format over token-addressed frames.
"""
from __future__ import annotations

import argparse
import asyncio
import bisect
from typing import Dict, List

from ..server.messages import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
)
from .transport import RealProcess

SET_TOKEN = "demo.set"
GET_TOKEN = "demo.get"
RANGE_TOKEN = "demo.getRange"
PING_TOKEN = "demo.ping"
METRICS_TOKEN = "demo.metrics"


class DemoKV:
    def __init__(self, proc: RealProcess):
        from ..core import telemetry

        self.proc = proc
        self._d: Dict[bytes, bytes] = {}
        #: per-op counters in the unified telemetry hub's TDMetric registry
        #: — served back as a Prometheus-style text exposition on
        #: METRICS_TOKEN (docs/observability.md), alongside whatever engine
        #: perf / batcher series this process registered
        self._td = telemetry.hub().tdmetrics
        proc.register(GET_TOKEN, self.get)
        proc.register(RANGE_TOKEN, self.get_range)
        proc.register(SET_TOKEN, self.set)
        proc.register(PING_TOKEN, self.ping)
        proc.register(METRICS_TOKEN, self.metrics)

    async def ping(self, body):
        return body

    async def metrics(self, _body) -> str:
        """Prometheus-style text exposition of this process's telemetry."""
        from ..core import telemetry

        return telemetry.hub().prometheus_text()

    async def set(self, body) -> bool:
        k, v = body
        self._td.int64("demo.sets").increment()
        if v is None:
            self._d.pop(k, None)
        else:
            self._d[k] = v
        return True

    async def get(self, req: GetValueRequest) -> GetValueReply:
        self._td.int64("demo.gets").increment()
        return GetValueReply(value=self._d.get(req.key))

    async def get_range(self, req: GetKeyValuesRequest) -> GetKeyValuesReply:
        keys = sorted(self._d)
        lo = bisect.bisect_left(keys, req.begin)
        hi = bisect.bisect_left(keys, req.end)
        rows: List = [(k, self._d[k]) for k in keys[lo:hi]]
        more = len(rows) > req.limit
        return GetKeyValuesReply(data=rows[: req.limit], more=more)


async def serve(host: str, port: int) -> None:
    proc = RealProcess(host, port)
    DemoKV(proc)
    await proc.start()
    print(f"listening on {proc.address}", flush=True)
    while True:
        await asyncio.sleep(3600)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    try:
        asyncio.run(serve(args.host, args.port))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
