"""A standalone KV node over the real transport — the two-OS-process
demo: run one of these per terminal, point a client at it over TCP.

    python -m foundationdb_tpu.real.demo_server --port 4500

Serves the storage-interface message types (GetValueRequest /
GetKeyValuesRequest) plus set/clear one-ways, all serialized with the
versioned flat wire format over token-addressed frames. With `--trace`
the process records spans for every op, joined to the caller's
propagated trace context (core/trace.py), and serves its bounded span
ring on the `trace.spans` token — the fetch channel `tools/cli.py trace`
and the cross-process waterfall reconstruction pull
(docs/observability.md "Distributed tracing").
"""
from __future__ import annotations

import argparse
import asyncio
import bisect
from typing import Dict, List

from ..core.trace import (
    SPANS_TOKEN,
    current_trace_context,
    g_spans,
    span_event,
    span_now,
)
from ..server.messages import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
)
from .transport import RealProcess

SET_TOKEN = "demo.set"
GET_TOKEN = "demo.get"
RANGE_TOKEN = "demo.getRange"
PING_TOKEN = "demo.ping"
METRICS_TOKEN = "demo.metrics"


class DemoKV:
    def __init__(self, proc: RealProcess):
        from ..core import telemetry

        self.proc = proc
        self._d: Dict[bytes, bytes] = {}
        #: per-op counters in the unified telemetry hub's TDMetric registry
        #: — served back as a Prometheus-style text exposition on
        #: METRICS_TOKEN (docs/observability.md), alongside whatever engine
        #: perf / batcher series this process registered
        self._td = telemetry.hub().tdmetrics
        proc.register(GET_TOKEN, self.get)
        proc.register(RANGE_TOKEN, self.get_range)
        proc.register(SET_TOKEN, self.set)
        proc.register(PING_TOKEN, self.ping)
        proc.register(METRICS_TOKEN, self.metrics)
        proc.register(SPANS_TOKEN, self.spans)

    @staticmethod
    def _trace_op(op: str, t0: float) -> None:
        """Record this op's server-side span joined to the caller's
        propagated trace (the transport installed the inbound context);
        a context-less or untraced request records nothing."""
        if not g_spans.enabled:
            return
        ctx = current_trace_context()
        if ctx is None:
            return
        span_event("server." + op, ctx.trace_id, t0, span_now(),
                   parent=ctx.parent)

    async def ping(self, body):
        t0 = span_now() if g_spans.enabled else 0.0
        self._trace_op("demo.ping", t0)
        return body

    async def metrics(self, _body) -> str:
        """Prometheus-style text exposition of this process's telemetry."""
        from ..core import telemetry

        return telemetry.hub().prometheus_text()

    async def spans(self, _body):
        """This process's bounded span ring (core/trace.export_spans)."""
        from ..core import trace

        return trace.export_spans()

    async def set(self, body) -> bool:
        t0 = span_now() if g_spans.enabled else 0.0
        k, v = body
        self._td.int64("demo.sets").increment()
        if v is None:
            self._d.pop(k, None)
        else:
            self._d[k] = v
        self._trace_op("demo.set", t0)
        return True

    async def get(self, req: GetValueRequest) -> GetValueReply:
        t0 = span_now() if g_spans.enabled else 0.0
        self._td.int64("demo.gets").increment()
        reply = GetValueReply(value=self._d.get(req.key))
        self._trace_op("demo.get", t0)
        return reply

    async def get_range(self, req: GetKeyValuesRequest) -> GetKeyValuesReply:
        t0 = span_now() if g_spans.enabled else 0.0
        keys = sorted(self._d)
        lo = bisect.bisect_left(keys, req.begin)
        hi = bisect.bisect_left(keys, req.end)
        rows: List = [(k, self._d[k]) for k in keys[lo:hi]]
        more = len(rows) > req.limit
        self._trace_op("demo.getRange", t0)
        return GetKeyValuesReply(data=rows[: req.limit], more=more)


async def serve(host: str, port: int, trace: bool = False) -> None:
    proc = RealProcess(host, port)
    DemoKV(proc)
    await proc.start()
    if trace:
        from ..core.trace import set_process_name, set_span_collection

        set_span_collection(True)
        set_process_name(f"demo:{proc.port}")
    print(f"listening on {proc.address}", flush=True)
    while True:
        await asyncio.sleep(3600)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--trace", action="store_true",
                    help="record spans (joined to propagated trace "
                         "contexts) and serve the ring on trace.spans")
    args = ap.parse_args(argv)
    try:
        asyncio.run(serve(args.host, args.port, trace=args.trace))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
