"""Real-cluster launcher + smoke check: `python -m foundationdb_tpu.real.cluster`.

Spawns N node processes (real/node.py — the first three double as
coordinators, matching fdbd()'s composition), waits for the cluster to
elect a controller and recover, then drives the Cycle workload's exact
semantics through a real client over TCP: K keys hold a ring permutation;
transactions read two adjacent links and rotate them; the final check
walks the ring and must visit every node exactly once. Exit code 0 iff
the cluster recovered, every transaction path worked (GRV, reads, commit,
retries), and the invariant held.

This is the round-3/4/5 VERDICT's missing deliverable: every role as an
OS process over the real transport with a protocol handshake — not sim.
"""
from __future__ import annotations

import argparse
import asyncio
import contextlib
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time


def free_ports(n: int) -> list:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@contextlib.asynccontextmanager
async def client_session(coords, seed: int):
    """Boot a real client (scheduler + net + Database + the asyncio task
    driving the cooperative loop) and tear it down in the one correct
    order: sockets closed, loop stopped, driver cancelled."""
    from ..client.database import Database
    from ..sim.loop import set_scheduler
    from .runtime import RealNetClient, RealScheduler

    sched = RealScheduler(seed=seed)
    set_scheduler(sched)
    net = RealNetClient(sched)
    db = Database(net, "client:0", coordinator_addrs=coords)
    run_task = asyncio.ensure_future(sched.run_async())
    try:
        yield sched, db
    finally:
        net.raw.close()
        sched.shutdown()
        run_task.cancel()


async def client_main(coords, n_keys: int, n_txns: int) -> None:
    from ..sim.loop import TaskPriority
    from .runtime import sim_to_aio

    async with client_session(coords, seed=1) as (sched, db):

        async def work():
            # setup: the identity ring
            async def init(tr):
                for i in range(n_keys):
                    tr.set(b"cyc/%04d" % i, b"%04d" % ((i + 1) % n_keys))
            await db.run(init)

            # rotate random adjacent links (the Cycle workload's txn)
            from ..sim.loop import current_scheduler

            rng = current_scheduler().rng
            for _ in range(n_txns):
                start = rng.random_int(0, n_keys)

                async def rotate(tr, s=start):
                    a = b"cyc/%04d" % s
                    b = await tr.get(a)
                    assert b is not None, f"missing link {a}"
                    c = await tr.get(b"cyc/" + b)
                    assert c is not None
                    d = await tr.get(b"cyc/" + c)
                    assert d is not None
                    # a->b->c->d becomes a->c->b->d
                    tr.set(a, c)
                    tr.set(b"cyc/" + c, b)
                    tr.set(b"cyc/" + b, d)
                await db.run(rotate)

            # check: one cycle visiting every node exactly once
            async def read_ring(tr):
                out = {}
                for i in range(n_keys):
                    v = await tr.get(b"cyc/%04d" % i)
                    assert v is not None
                    out[i] = int(v)
                return out
            ring = await db.run(read_ring)
            seen = set()
            at = 0
            for _ in range(n_keys):
                assert at not in seen, "ring collapsed: revisited node"
                seen.add(at)
                at = ring[at]
            assert at == 0 and len(seen) == n_keys, "broken ring permutation"
            return True

        t = sched.spawn(work(), TaskPriority.DEFAULT_ENDPOINT, name="smoke")
        ok = await asyncio.wait_for(sim_to_aio(t), timeout=180.0)
        assert ok is True


async def backup_client_main(coords, blob_root: str) -> None:
    """End-to-end backup→wipe→restore against a REAL cluster with a
    blobstore:// target (backup/http_blob.py): seed rows, start the live
    backup, mutate (sets + a clear) so the mutation log carries real
    traffic past the snapshot, snapshot + finish, wipe the keyspace, then
    restore into the same cluster and verify byte-for-byte."""
    from ..backup.agent import BackupAgent
    from ..backup.http_blob import HTTPBlobServer
    from . import tls

    # the blobstore rides the same TLS policy as the cluster when one is
    # set — `--tls --backup` must not leak the keyspace in plaintext
    srv = HTTPBlobServer(blob_root, ssl_context=tls.server_context())
    await srv.start()
    agent = None
    try:
        async with client_session(coords, seed=2) as (sched, db):
            agent = BackupAgent(None, db, f"blobstore://127.0.0.1:{srv.port}")
            await _backup_drill(sched, db, agent)
    finally:
        if agent is not None:
            agent.close()
        await srv.stop()


async def _backup_drill(sched, db, agent) -> None:
    from ..sim.loop import TaskPriority
    from .runtime import sim_to_aio
    from ..layers import read_all

    async def read_user_rows(tr):
        return await read_all(tr, b"", b"\xff", page=200)

    def _stage(msg: str) -> None:
        print(f"backup-smoke: {msg}", flush=True)

    async def work():
        async def seed(tr):
            for i in range(40):
                tr.set(b"bk/%04d" % i, b"v%04d" % i)
        await db.run(seed)
        _stage("seeded")

        await agent.start_backup()
        _stage("backup started")

        async def live(tr):
            for i in range(10):
                tr.set(b"bk/live/%02d" % i, b"L%02d" % i)
            tr.clear_range(b"bk/0000", b"bk/0005")
        await db.run(live)
        _stage("live mutations committed")

        await agent.snapshot(chunks=4, workers=2)
        _stage("snapshot done")
        await agent.finish_backup()
        _stage("backup finished")

        expected = await db.run(read_user_rows)
        assert len(expected) == 45, len(expected)   # 40 - 5 + 10

        async def wipe(tr):
            tr.clear_range(b"", b"\xff")
        await db.run(wipe)
        assert await db.run(read_user_rows) == []
        _stage("wiped")

        await agent.restore(db)
        _stage("restored")
        got = await db.run(read_user_rows)
        assert got == expected, (len(got), len(expected))
        return True

    t = sched.spawn(work(), TaskPriority.DEFAULT_ENDPOINT, name="backup-smoke")
    ok = await asyncio.wait_for(sim_to_aio(t), timeout=180.0)
    assert ok is True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="real cluster over TCP + smoke check")
    ap.add_argument("--procs", type=int, default=4, help="worker node count")
    ap.add_argument("--keys", type=int, default=20)
    ap.add_argument("--txns", type=int, default=30)
    ap.add_argument("--engine", default="native", choices=["native", "oracle"])
    ap.add_argument("--keep-datadir", action="store_true")
    ap.add_argument("--backup", action="store_true",
                    help="run the backup->wipe->restore smoke against a "
                         "blobstore:// HTTP container instead of Cycle")
    ap.add_argument("--tls", action="store_true",
                    help="mutual TLS on every connection: generated CA + "
                         "shared node cert, subject-checked both ways")
    args = ap.parse_args(argv)

    n = max(args.procs, 4)   # recruitment needs storage + txn workers
    ports = free_ports(n)
    coords = [f"127.0.0.1:{p}" for p in ports[:min(3, n)]]
    datadir = tempfile.mkdtemp(prefix="fdb_tpu_real_")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")   # nodes never touch the TPU
    tls_cfg = None
    if args.tls:
        from .tls import generate_test_credentials, set_tls
        tls_cfg = generate_test_credentials(os.path.join(datadir, "tls"))
        set_tls(tls_cfg)   # the smoke client speaks TLS too
    procs = []
    try:
        for i, port in enumerate(ports):
            cmd = [
                sys.executable, "-m", "foundationdb_tpu.real.node",
                "--port", str(port),
                "--coordinators", ",".join(coords),
                "--datadir", os.path.join(datadir, str(port)),
                "--workers", str(n),
                "--engine", args.engine,
            ]
            if tls_cfg is not None:
                cmd += ["--tls-cert", tls_cfg.cert_path,
                        "--tls-key", tls_cfg.key_path,
                        "--tls-ca", tls_cfg.ca_path,
                        "--tls-verify", tls_cfg.verify_rules]
            if i < len(coords):
                cmd += ["--cc-priority", str(i)]
            procs.append(subprocess.Popen(
                cmd, env=env, cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

        # wait for every node to accept connections (the cluster join path);
        # deadline knob-driven like the rest of the real_rpc_timeout_s
        # family, and on the monotonic clock — a wall-clock step (NTP, VM
        # resume) must not expire the probe early
        from ..core import buggify
        from ..core.knobs import FLOW_KNOBS

        deadline = time.monotonic() + FLOW_KNOBS.real_cluster_boot_timeout_s
        for port in ports:
            while True:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"node on port {port} never came up")
                if buggify.buggify():
                    # slow joiner: the probe itself lags, so nodes come up
                    # in a different order than they were spawned
                    time.sleep(0.1)
                try:
                    with socket.create_connection(("127.0.0.1", port), timeout=1.0):
                        if buggify.buggify():
                            # join flap: drop the successful probe once and
                            # re-probe — the node must tolerate a client
                            # connecting and vanishing mid-join
                            time.sleep(0.05)
                            continue
                        break
                except OSError:
                    time.sleep(0.3)

        if args.backup:
            asyncio.run(backup_client_main(
                coords, os.path.join(datadir, "blobstore")))
            print(f"REAL CLUSTER OK: {n} nodes, backup->wipe->restore "
                  f"via blobstore verified", flush=True)
        else:
            asyncio.run(client_main(coords, args.keys, args.txns))
            print(f"REAL CLUSTER OK: {n} nodes, {args.txns} cycle txns, "
                  f"ring intact", flush=True)
        return 0
    except BaseException as e:  # noqa: BLE001 — report, then tear down
        print(f"REAL CLUSTER FAILED: {type(e).__name__}: {e}", flush=True)
        for p in procs:
            if p.poll() is None:
                continue
            out = p.stdout.read() if p.stdout else ""
            print(f"--- dead node (rc={p.returncode}):\n{out[-2000:]}", flush=True)
        return 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if not args.keep_datadir:
            shutil.rmtree(datadir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
