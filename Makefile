# Gate before every commit/snapshot: the deterministic-sim methodology is
# the product — a red suite must never ship (round-3 lesson).
check:
	python -m pytest tests/ -q -m 'not slow'

bench:
	python bench.py

# CPU-backend perf-path smoke (seconds): bucket-ladder serving drive with
# oracle parity + zero-steady-state-compile assertion, and a mini
# latency-under-load curve through the e2e sim cluster with injected
# device times (docs/perf.md). Breaks loudly in CI when perf wiring rots.
bench-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.bench_smoke

# Observability-path smoke (docs/observability.md): commit-path spans
# attribute client latency within tolerance, unified telemetry drains to
# \xff/metrics/, the flight recorder populates, and disabled tracing stays
# near-zero-cost.
telemetry-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.telemetry_smoke

# Device-fault chaos: the full multi-seed nemesis campaign (slow tier; the
# 3-seed smoke rides `check`) + the buggify coverage report over the
# grinder battery (docs/fault_tolerance.md).
chaos:
	python -m pytest tests/test_device_nemesis.py -q -m slow
	python -m foundationdb_tpu.tools.buggify_coverage --seeds 4 --min-frac 0.5

# Keyspace-heat smoke (docs/observability.md "Keyspace heat &
# occupancy", ~45s CPU): a planted hot-key stream must surface its keys
# at the top of the aggregated hot ranges, suggested split points must
# partition the measured load within tolerance, the Prometheus
# exposition (heat.* + engine verdict split) must pass the strict PR 8
# parser, and the disabled path (resolver_heat_buckets=0) must build no
# aggregator and emit no heat outputs from any program.
heat-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.heat_smoke

# Conflict-scheduler smoke (docs/scheduling.md, seconds, solo CPU): a
# planted hot-key A/B must serve a materially lower abort fraction with
# the scheduler on at an equal-or-better commit count, the scheduled
# dispatch journal must replay bit-for-bit through a clean serial
# oracle, the fdbtpu_sched exposition must pass the strict parser, and
# the disabled path must be an inert FIFO with no telemetry series.
sched-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.sched_smoke

# Distributed-tracing smoke (docs/observability.md "Distributed
# tracing", seconds): boots a 2-OS-process cluster (a --serve traced
# commit server child), drives a traced fleet, asserts >= 1
# cross-process waterfall reconstructs with the sum identity, the
# disabled-span allocation guard still passes with context propagation
# compiled in, and the exported Chrome trace JSON loads (schema check).
trace-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.trace_smoke

# Performance-observatory smoke (docs/observability.md "Performance
# observatory", ~30s CPU): the compile & memory ledger populates on
# warmup with analysis fields, sampled device timing stays observational
# (abort parity on/off, blocking_syncs == 0, zero post-warmup compiles
# with sampling baked in) and lands within sanity bounds of the
# loop-floor figure, and bench_history parses every committed
# BENCH_r*.json with the regression gate green.
perf-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.perf_smoke

# Cluster-watchdog smoke (docs/observability.md "Watchdog, burn rates &
# incidents", ~30s, solo-CPU safe — pure host-side, no jax): a synthetic
# telemetry replay on a virtual clock drives every rule class
# (threshold, staleness, anomaly band, multi-window burn rate) through
# pending -> firing -> resolved, the burn-rate arithmetic is checked
# against a hand computation, same-seed replays produce bit-equal
# incident timelines, and the `fdbtpu_alerts` exposition passes the
# strict PR 8 line parser.
watch-smoke:
	python -m foundationdb_tpu.tools.watch_smoke

# Bench-artifact trend gate (docs/observability.md "Performance
# observatory"): per-section trend tables over the committed BENCH_r*.json
# series with noise-aware verdicts — >10% regressions on headline metrics
# against the previous SAME-PLATFORM artifact fail, naming the section
# and metric. Cluster-less; `cli bench-history` is the same run.
bench-history:
	python -m foundationdb_tpu.tools.bench_history

# Incremental-history smoke (docs/perf.md "Incremental history
# maintenance", ~30s, solo-CPU safe): isolated apply_writes_and_gc cost
# at two capacities proves tiered apply scales with the batch not the
# table, zero post-warmup compiles across several lazy compactions, a
# monolithic/tiered/oracle parity canary, and a strict parse of the
# fdbtpu_history Prometheus family.
history-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.history_smoke

# Online-resharding smoke (docs/elasticity.md, ~45s, solo-CPU safe — one
# process, no sockets, do not overlap with tier-1): synthetic drift
# against REAL jax engines drives one split AND one merge end-to-end
# through the live handoff protocol, asserts every blackout under
# reshard_blackout_budget_ms (controller clocks AND reshard.blackout
# trace segments), zero post-warmup compiles on untouched shards,
# bit-identical shard-journal oracle replay (handoff batches included),
# and a strict parse of the fdbtpu_reshard Prometheus family.
reshard-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.reshard_smoke

# Measured-mesh smoke (docs/perf.md "Measured mesh resolution", ~45s,
# solo-CPU safe — one process, no sockets, do not overlap with tier-1):
# forces 8 XLA host devices and drives the mesh engine's full
# split -> exchange -> apply arc on REAL jax engines behind an elastic
# group: oracle parity live and via journal replay across a device-shard
# epoch flip, blocking_syncs == 0 in the overlapped exchange ring, zero
# post-warmup compiles, measured exchange intervals + device view,
# measured-split adoption from a skewed heat histogram, and a strict
# parse of the fdbtpu_mesh Prometheus family.
mesh-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.mesh_smoke

# Diurnal drift campaigns (docs/elasticity.md): the live-elasticity SLO
# gate — 2 seeds x {jax, device_loop} wall-clock campaigns where the hot
# range DRIFTS across the keyspace while the heat-driven controller
# splits/merges resolver shards on the live cluster. assert_slos
# additionally requires >= 2 executed reshards per campaign with every
# per-range blackout inside budget. Solo-CPU: do not overlap with tier-1.
chaos-drift:
	mkdir -p _artifacts
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.real.nemesis \
		--drift --seeds 2 --engine-modes jax,device_loop --watchdog \
		--blackbox-dir _artifacts/chaos_drift_blackbox \
		--json _artifacts/chaos_drift_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		shards _artifacts/chaos_drift_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		blackbox _artifacts/chaos_drift_report.json

# Commit-forensics smoke (docs/observability.md "Black-box journal &
# forensics", ~30s, solo-CPU safe — oracle engines, one process): a short
# chaos campaign with the black-box journal on (elastic + reshard +
# watchdog), then: explain the worst retained ack (>= 5 signal sources
# joined), differential-replay the persisted window through the clean
# serial oracle (verdict-bit-identical, across the epoch flip), and
# strict-parse every frame against BLACKBOX_EVENT_REGISTRY.
forensics-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.forensics_smoke

# Crash-restart campaigns (docs/fault_tolerance.md "Crash-stop
# recovery"): 2 seeds x {jax, device_loop} — a recoverable commit-server
# child (journal fsync_interval=1 + cadenced snapshots + on-disk
# progcache) killed -9 mid-load under injected disk faults, supervised
# back up, and machine-asserted to recover inside
# resolver_recovery_budget_ms (span-verified), serve NEW commits, and
# replay the whole retained batch stream bit-identical through the clean
# serial oracle. Solo-CPU: do not overlap with tier-1.
chaos-crash:
	mkdir -p _artifacts
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.real.nemesis \
		--crash --seeds 2 --engine-modes jax,device_loop \
		--blackbox-dir _artifacts/chaos_crash_blackbox \
		--json _artifacts/chaos_crash_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		recovery _artifacts/chaos_crash_report.json

# Crash-stop recovery smoke (~30s, solo-CPU safe — one parent + one
# supervised child on the miniature jax ladder): ONE seeded kill -9 ->
# supervised restart -> recovery-inside-budget arc, with progcache
# rewarm, cross-crash oracle replay parity and the `cli recovery`
# render asserted end to end.
crash-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.crash_smoke

# Scenario-atlas smoke (docs/scenarios.md, ~45s, solo-CPU safe — oracle
# engines, one process): two miniature recipes (flash_sale,
# session_cache) run end-to-end through run_campaign with scorecards
# machine-asserted green (every SLO contract row, journal replay parity,
# all incidents explained), the flash-sale signature measurably hotter
# than the cache's, `cli atlas` rendering both the live gauges and the
# report file, and a strict parse of the fdbtpu_scenario Prometheus
# family. Campaign artifacts land under gitignored _artifacts/.
atlas-smoke:
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.atlas_smoke

# Static invariant check (docs/static_analysis.md, ~2s, pure AST — never
# imports jax): determinism, host-sync discipline, donation safety,
# recompile hazards, knob/doc drift, span + blackbox registries.
# Non-zero on any non-baselined finding or stale baseline entry; the
# same run rides tier-1 as tests/test_lint.py::test_repo_clean.
lint:
	python -m foundationdb_tpu.tools.lint

# Wall-clock chaos (docs/real_cluster.md): seeded nemesis campaigns against
# the REAL transport under jax AND device_loop engine modes — every SLO
# machine-asserted (p99 outside injected-fault windows <= the budget-knob
# product, bit-identical oracle journal replay, blocking_syncs == 0,
# >= 1 failover AND swap-back, supervised child restart) — plus the
# served_under_chaos Zipf sweep (admission holds p99 in budget; the
# uncontrolled runs must blow it). Every campaign exports tail-sampled
# cross-process Chrome trace JSON (chaos_real_traces/; `cli trace FILE`
# renders one). Solo-CPU: do not overlap with tier-1.
chaos-real:
	mkdir -p _artifacts
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.real.nemesis \
		--seeds 2 --engine-modes jax,device_loop --sweep --watchdog \
		--trace-dir _artifacts/chaos_real_traces \
		--blackbox-dir _artifacts/chaos_real_blackbox \
		--json _artifacts/chaos_real_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		chaos-status _artifacts/chaos_real_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		incidents _artifacts/chaos_real_report.json
	JAX_PLATFORMS=cpu python -m foundationdb_tpu.tools.cli \
		explain --slo _artifacts/chaos_real_report.json

.PHONY: check bench bench-smoke telemetry-smoke heat-smoke sched-smoke trace-smoke chaos chaos-real chaos-drift chaos-crash reshard-smoke mesh-smoke lint perf-smoke bench-history watch-smoke forensics-smoke crash-smoke atlas-smoke history-smoke
