# Gate before every commit/snapshot: the deterministic-sim methodology is
# the product — a red suite must never ship (round-3 lesson).
check:
	python -m pytest tests/ -q

bench:
	python bench.py

.PHONY: check bench
