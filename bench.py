"""North-star benchmark: resolved transactions/sec/chip for the TPU conflict
kernel (the analog of `fdbserver -r skiplisttest`, SkipList.cpp:1412-1502,
which measures ConflictBatch::detectConflicts in isolation).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline is against the BASELINE.json north star of 10M resolved txns/sec
on a v5e-8, i.e. 1.25M txns/sec/chip.

Workload shape mirrors the Cycle/RandomReadWrite configs: single-key reads +
single-key writes over a hot key pool (16-byte keys like the reference's
performance.rst setup), full device batches, GC horizon trailing by a few
batches so the boundary table reaches a steady state.

Throughput is measured with the batches device-resident and the step loop
inside one long lax.scan: this measures the device's sustained resolve rate,
not the per-dispatch overhead of the host link (the tunneled dev TPU's
round-trip is ~100ms per dispatch; production resolvers sit next to their
chip). device_ms_per_batch is the amortized per-batch device time;
p99_link_ms is per-call latency through the tunnel and is dominated by it.
"""
import argparse
import dataclasses
import json
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from foundationdb_tpu.ops import conflict_kernel as ck

BASELINE_TXNS_PER_SEC_PER_CHIP = 10_000_000 / 8

CFG = ck.KernelConfig(
    key_words=4,          # 16-byte window fits the 16B bench keys exactly; point
                          # range ends are device-synthesized via the length
                          # lane (_bump), so they never need a 5th word
    capacity=24576,       # steady state holds ~2 boundaries per hot pool key
                          # (~16.4k rows); 24576 leaves 50% headroom and keeps
                          # the merge/GC sweeps and search sort 25% smaller
                          # than the old 1<<15
    max_point_reads=8192,
    max_point_writes=8192,
    max_reads=256,        # range rows: present but small (point-heavy config,
    max_writes=256,       # like the reference's Cycle/RandomReadWrite shape)
    max_txns=4096,
    fixpoint="pallas",    # ONE fused kernel for the commit fixpoint
                          # (ops/fixpoint_pallas.py) instead of ~5.4
                          # launch-bound while_loop iterations: 4.5 -> 3.2ms
)
READS_PER_TXN = 2
WRITES_PER_TXN = 2
POOL = 8192               # hot-key pool; steady-state boundaries stay < capacity
N_DISTINCT_BATCHES = 8
SCAN_STEPS = 768          # one compiled program: scan of this many batches
                          # (long enough that the ~120ms tunnel dispatch
                          # round-trip inflates the per-batch figure by
                          # <0.2ms; measured device time is ~3.9ms/batch)
THROUGHPUT_SCANS = 2      # dispatch round-trip through the tunneled dev chip
                          # is ~100ms; long scans amortize it away
LATENCY_STEPS = 20
VERSIONS_PER_BATCH = CFG.max_txns
GC_LAG_BATCHES = 4

#: active measurement profile ("chip" | "cpu"); apply_profile() resolves
#: it before anything compiles
PROFILE = "chip"
#: latency-curve sweep shapes + scan length (profile-scaled)
CURVE_SHAPES = (512, 1024, 2048, 4096)
CURVE_SCAN_STEPS = 256


def apply_profile(name: str) -> str:
    """Resolve + apply the measurement profile. "chip" is the historical
    configuration (pallas fixpoint, long scans — the tunneled-TPU
    methodology every BENCH_r<=05 used). "cpu" records the same sections
    HONESTLY on the CPU backend: the xla fixpoint (the pallas interpreter
    is a compiler benchmark, not an engine one), shorter scans, and the
    infeasible-on-CPU weak-scale extrapolation skipped. The artifact
    carries `profile` + `device`, and tools/bench_history.py compares
    artifacts only within the same platform — a CPU artifact never reads
    as a regression against a TPU one, nor vice versa."""
    global PROFILE, CFG, SCAN_STEPS, THROUGHPUT_SCANS, LATENCY_STEPS
    global CURVE_SHAPES, CURVE_SCAN_STEPS, HARNESS_SHAPES, HARNESS_SCAN_STEPS
    if name == "auto":
        name = "cpu" if jax.default_backend() == "cpu" else "chip"
    PROFILE = name
    if name == "cpu":
        CFG = dataclasses.replace(CFG, fixpoint="xla")
        SCAN_STEPS = 48
        THROUGHPUT_SCANS = 1
        LATENCY_STEPS = 5
        CURVE_SHAPES = (512, 1024, 2048)
        CURVE_SCAN_STEPS = 64
        HARNESS_SHAPES = (512, 768, 1024)
        HARNESS_SCAN_STEPS = 128
    return name


def synth_batches_for(cfg, rng: np.random.Generator, n_rows: int = 0,
                      pool_n: int = POOL):
    """Device batches synthesized directly in packed form (no host bytes).
    Reads/writes are POINT rows ([k, k+'\\x00')), the Cycle/RandomReadWrite
    shape; the range-row groups ride along empty. `n_rows` valid rows per
    group (default: the full caps); `pool_n` keys in the hot pool (the
    per-shard measurement draws from its shard's 1/8 slice)."""
    K = cfg.lanes
    Rp, Wp, T = cfg.rp, cfg.wp, cfg.max_txns
    Rr, Wr = cfg.max_reads, cfg.max_writes
    n_rows = n_rows or Rp
    pool = np.zeros((pool_n, K), np.uint32)
    pool[:, :4] = rng.integers(0, 2**32, size=(pool_n, 4), dtype=np.uint32)
    pool[:, K - 1] = 16                  # 16-byte keys (length lane)
    pool = pool[np.lexsort([pool[:, c] for c in range(K - 1, -1, -1)])]

    def txn_of_rows(n):
        if n == T * READS_PER_TXN:
            return np.repeat(np.arange(T, dtype=np.int32), READS_PER_TXN)
        return np.sort(rng.integers(0, T, size=n)).astype(np.int32)

    batches = []
    for _ in range(N_DISTINCT_BATCHES):
        rpb = np.zeros((Rp, K), np.uint32)
        wpb = np.zeros((Wp, K), np.uint32)
        rpb[:n_rows] = pool[rng.integers(0, pool_n, size=n_rows)]
        wpb[:n_rows] = pool[rng.integers(0, pool_n, size=n_rows)]
        rp_txn = np.zeros((Rp,), np.int32)
        wp_txn = np.zeros((Wp,), np.int32)
        rp_txn[:n_rows] = txn_of_rows(n_rows)
        wp_txn[:n_rows] = txn_of_rows(n_rows)
        batches.append({
            "rpb": rpb,
            "rp_txn": rp_txn,
            "rp_valid": np.arange(Rp) < n_rows,
            "rb": np.zeros((Rr, K), np.uint32),
            "re": np.zeros((Rr, K), np.uint32),
            "r_snap": np.zeros((Rr,), np.int32),
            "r_txn": np.zeros((Rr,), np.int32),
            "r_valid": np.zeros((Rr,), bool),
            "wpb": wpb,
            "wp_txn": wp_txn,
            "wp_valid": np.arange(Wp) < n_rows,
            "wb": np.zeros((Wr, K), np.uint32),
            "we": np.zeros((Wr, K), np.uint32),
            "w_txn": np.zeros((Wr,), np.int32),
            "w_valid": np.zeros((Wr,), bool),
            "t_ok": np.ones((T,), bool),
            "t_too_old": np.zeros((T,), bool),
        })
    # Stack to [B, ...] for device residency + scan.
    return jax.device_put(jax.tree.map(lambda *xs: np.stack(xs), *batches))


def synth_batches(rng: np.random.Generator):
    return synth_batches_for(CFG, rng)


def measure_scan(cfg, scan_steps: int = 256, n_rows: int = 0,
                 pool_n: int = POOL, seed: int = 2026) -> float:
    """Amortized device ms/batch for `cfg` on a steady-state table: one
    compiled scan of `scan_steps` resolve_steps over device-resident
    batches (the same methodology as the headline number)."""
    rng = np.random.default_rng(seed)
    bb = synth_batches_for(cfg, rng, n_rows=n_rows, pool_n=pool_n)
    T = cfg.max_txns

    def versioned(batch, now):
        snap = jnp.maximum(now - T // 2, 0)
        gc = jnp.maximum(now - GC_LAG_BATCHES * T, 0)
        return dict(batch,
                    rp_snap=jnp.full((cfg.rp,), snap, jnp.int32),
                    now=jnp.asarray(now, jnp.int32),
                    gc=jnp.asarray(gc, jnp.int32))

    def step(carry, i):
        state, now = carry
        batch = jax.tree.map(lambda x: x[i % N_DISTINCT_BATCHES], bb)
        state, out = ck.resolve_step(cfg, state, versioned(batch, now))
        gc_applied = jnp.maximum(now - GC_LAG_BATCHES * T, 0)
        return (state, now + T - gc_applied), (out["n"], out["overflow"])

    run = jax.jit(lambda st, now: lax.scan(step, (st, now), jnp.arange(scan_steps)),
                  donate_argnums=(0,))
    state = jax.device_put(ck.initial_state(cfg))
    (state, now), (ns, ov) = run(state, jnp.int32(1))
    _ = np.asarray(ns)
    assert not np.any(np.asarray(ov)), "overflow during warmup"
    t0 = time.perf_counter()
    (state, now), (ns, ov) = run(state, now)
    _ = np.asarray(ns)
    return (time.perf_counter() - t0) / scan_steps * 1e3


def versioned(batch, now):
    """Attach device-computed version fields (resolver batch at version now)."""
    snap = jnp.maximum(now - VERSIONS_PER_BATCH // 2, 0)
    gc = jnp.maximum(now - GC_LAG_BATCHES * VERSIONS_PER_BATCH, 0)
    return dict(
        batch,
        rp_snap=jnp.full((CFG.rp,), snap, jnp.int32),
        now=jnp.asarray(now, jnp.int32),
        gc=jnp.asarray(gc, jnp.int32),
    )


def step_fn(carry, i):
    state, now = carry
    batch = jax.tree.map(lambda x: x[i % N_DISTINCT_BATCHES], BATCHES)
    state, out = ck.resolve_step(CFG, state, versioned(batch, now))
    # GC with gc > 0 rebases stored versions by gc (the host engine's `base`
    # bookkeeping); carry base-relative time so snapshots/GC stay in frame.
    gc_applied = jnp.maximum(now - GC_LAG_BATCHES * VERSIONS_PER_BATCH, 0)
    return (state, now + VERSIONS_PER_BATCH - gc_applied), (out["n"], out["overflow"])


def main(argv=None):
    global BATCHES
    ap = argparse.ArgumentParser(description="fdb-tpu north-star benchmark")
    ap.add_argument("--profile", choices=("auto", "chip", "cpu"),
                    default="auto",
                    help="measurement profile (auto = cpu when the CPU "
                         "backend is the only device; see apply_profile)")
    args = ap.parse_args(argv)
    apply_profile(args.profile)
    dev = jax.devices()[0]
    rng = np.random.default_rng(2026)
    BATCHES = synth_batches(rng)
    state = jax.device_put(ck.initial_state(CFG))

    run = jax.jit(
        lambda st, now: lax.scan(step_fn, (st, now), jnp.arange(SCAN_STEPS)),
        donate_argnums=(0,),
    )
    single = jax.jit(
        lambda st, now: ck.resolve_step(
            CFG, st, versioned(jax.tree.map(lambda x: x[0], BATCHES), now)
        ),
        donate_argnums=(0,),
    )

    # Warm both programs (compile + first run happen here). Starting at 1,
    # base-relative `now` stabilizes near (GC_LAG_BATCHES+1)*VERSIONS_PER_BATCH.
    # Syncs use host transfers: block_until_ready returns before execution
    # completes on the tunneled dev-chip platform.
    (state, now), ns = run(state, jnp.int32(1))
    _ = np.asarray(ns)
    state, out = single(state, now)
    _ = np.asarray(out["status"])
    now = now + VERSIONS_PER_BATCH

    t0 = time.perf_counter()
    all_ns = []
    for _ in range(THROUGHPUT_SCANS):
        (state, now), ns = run(state, now)
        all_ns.append(ns)
    ns_host = np.asarray(all_ns[-1][0])
    dt = time.perf_counter() - t0
    for ns in all_ns:
        assert not np.any(np.asarray(ns[1])), "boundary table overflowed mid-bench"
    assert ns_host[-1] > 0
    txns_per_sec = THROUGHPUT_SCANS * SCAN_STEPS * CFG.max_txns / dt

    # Per-call latency (includes host link / dispatch overhead — on the
    # tunneled dev chip the link RTT alone is ~100ms; production resolvers
    # sit next to their chip, so device time per batch is the honest
    # latency number and is reported separately).
    lat = []
    for _ in range(LATENCY_STEPS):
        t1 = time.perf_counter()
        state, out = single(state, now)
        out["status"].copy_to_host_async()
        _ = np.asarray(out["status"])
        lat.append(time.perf_counter() - t1)
        now = now + VERSIONS_PER_BATCH - jnp.maximum(now - GC_LAG_BATCHES * VERSIONS_PER_BATCH, 0)
    p99_ms = float(np.percentile(np.asarray(lat) * 1e3, 99))
    device_ms_per_batch = dt / (THROUGHPUT_SCANS * SCAN_STEPS) * 1e3

    # alloc = per-chunk np.zeros (the pre-arena cost); the arena figure is
    # what the serving path pays now and feeds every downstream estimate
    host_pack_alloc_ms = host_packing_ms_per_batch()
    host_pack_ms = host_packing_ms_per_batch(arena=True)
    parity_ok = parity_measurement_set()
    weak8 = sharded_tpu_weak_scale()
    ladder = bucket_ladder_section()
    curve = latency_curve(host_pack_ms)
    under_load = latency_under_load(host_pack_ms, curve)
    loop_floor = loop_floor_section()
    compile_memory = compile_memory_section()
    attribution = latency_attribution(host_pack_ms, under_load, loop_floor,
                                      compile_memory)
    # Sequential estimate (host pack, then device) and the pipelined rate: a
    # production resolver packs batch i+1 on the host while the device runs
    # batch i (JAX async dispatch gives the overlap for free — the host-side
    # work is two native C passes + numpy, no device sync in between), so
    # the sustained rate is governed by whichever side is slower.
    e2e = CFG.max_txns / ((device_ms_per_batch + host_pack_ms) / 1e3)
    e2e_pipelined = CFG.max_txns / (max(device_ms_per_batch, host_pack_ms) / 1e3)
    native_cpu = native_baseline_txns_per_sec()
    sharded = sharded_cpu_numbers()
    sharded_measured = sharded_measured_numbers()
    floor = history_floor_section()
    chaos_served = served_under_chaos_section()
    while_resharding = served_while_resharding_section()
    heat = conflict_heat_section()
    sched = conflict_scheduling_section()
    recovery = recovery_section()
    atlas = scenario_atlas_section()

    print(json.dumps({
        "metric": "resolved_txns_per_sec_per_chip",
        "value": round(txns_per_sec, 1),
        "unit": "txn/s",
        "vs_baseline": round(txns_per_sec / BASELINE_TXNS_PER_SEC_PER_CHIP, 4),
        "device_ms_per_batch": round(device_ms_per_batch, 3),
        "host_pack_ms_per_batch": round(host_pack_ms, 3),
        "host_pack_ms_per_batch_alloc": round(host_pack_alloc_ms, 3),
        "host_pack_arena_speedup": round(host_pack_alloc_ms / host_pack_ms, 3)
            if host_pack_ms > 0 else None,
        "e2e_txns_per_sec_est": round(e2e, 1),
        "e2e_pipelined_txns_per_sec": round(e2e_pipelined, 1),
        "parity_configs_ok": parity_ok,
        "p99_link_ms": round(p99_ms, 3),
        "batch_txns": CFG.max_txns,
        "native_cpu_txns_per_sec": native_cpu,
        "vs_native_cpu": round(txns_per_sec / native_cpu, 2) if native_cpu else None,
        "sharded_cpu_mesh": sharded,
        "sharded_measured": sharded_measured,
        "sharded_tpu_weak_scale": weak8,
        "bucket_ladder": ladder,
        "history_floor": floor,
        "loop_floor": loop_floor,
        "latency_curve": curve,
        "latency_under_load": under_load,
        "latency_attribution": attribution,
        "served_under_chaos": chaos_served,
        "served_while_resharding": while_resharding,
        "conflict_heat": heat,
        "conflict_scheduling": sched,
        "recovery": recovery,
        "scenario_atlas": atlas,
        "compile_memory": compile_memory,
        "profile": PROFILE,
        "device": str(dev),
    }))


#: weak-scaled 8-shard per-shard program (the north-star v5e-8 config):
#: global batch T=16384, per-shard rows = 16384*2/8 = 4096 (+8 sigma cap),
#: per-shard table = the keyspace's 1/8 slice. The fused Pallas fixpoint
#: runs per shard; on the mesh its per-iteration blocked-count reduction
#: rides lax.psum (the dryrun_multichip-validated topology).
WEAK8_T = 16384
WEAK8_CFG = ck.KernelConfig(
    key_words=4, capacity=3072,
    max_point_reads=4608, max_point_writes=4608,
    max_reads=64, max_writes=64,
    max_txns=WEAK8_T, fixpoint="pallas",
)
#: ICI collective budget per batch for the extrapolation: one [T] i32
#: hist-hits psum + ~5 fixpoint rounds of [T] i32 blocked counts = 6 x
#: (64KB / ~45GB/s per v5e ICI link + ~20us launch+latency) — rounded UP.
#: An ESTIMATE, used only by the chip-era weak-scale extrapolation; the
#: `sharded_measured` section carries the MEASURED per-psum collective
#: at each mesh width on this machine's platform (tools/mesh_bench.py).
WEAK8_COLLECTIVE_MS = 0.15


def sharded_tpu_weak_scale():
    """Per-shard wall time ON THE REAL CHIP at the weak-scaled 8-shard
    configuration, and the v5e-8 extrapolation: every shard runs this
    program concurrently on its own chip (same global batch), so the
    system rate is T / (per-shard wall + collectives). The CPU-mesh
    total-compute ratio (sharded_cpu_mesh) independently shows the
    sharding tax; collectives are estimated (documented above) because
    this environment has one physical chip."""
    if PROFILE == "cpu":
        # a 16384-txn pallas-fixpoint scan is a many-minute compiler
        # benchmark on CPU, and the extrapolation is only meaningful from
        # chip silicon — the section stays absent rather than misleading
        return None
    try:
        per_shard_ms = measure_scan(WEAK8_CFG, scan_steps=256,
                                    n_rows=2 * WEAK8_T // 8,
                                    pool_n=POOL // 8)
    except Exception:
        return None
    wall = per_shard_ms + WEAK8_COLLECTIVE_MS
    return {
        "per_shard_ms": round(per_shard_ms, 4),
        "collective_est_ms": WEAK8_COLLECTIVE_MS,
        "batch_txns": WEAK8_T,
        "v5e8_extrapolated_txns_per_sec": round(WEAK8_T / (wall / 1e3), 1),
        "vs_10M_target": round(WEAK8_T / (wall / 1e3) / 10_000_000, 4),
    }


def latency_curve(host_pack_ms_at_headline: float):
    """Resolver latency vs batch size (VERDICT r4 #2): device ms/batch for
    T in {512,1024,2048,4096} at the headline key pool, host-pack charged
    pro-rata (the native pack passes are linear in rows), and the chosen
    production point: the largest batch with device+pack <= 1.5ms — the
    resolver's share of the reference's < 3ms end-to-end commit budget
    (performance.rst:36,49)."""
    out = []
    for T in CURVE_SHAPES:
        cfg = ck.KernelConfig(
            key_words=4, capacity=CFG.capacity,
            max_point_reads=2 * T, max_point_writes=2 * T,
            max_reads=64, max_writes=64, max_txns=T, fixpoint=CFG.fixpoint,
        )
        try:
            dev_ms = measure_scan(cfg, scan_steps=CURVE_SCAN_STEPS)
        except Exception:
            continue
        pack_ms = host_pack_ms_at_headline * T / CFG.max_txns
        out.append({
            "batch_txns": T,
            "device_ms": round(dev_ms, 4),
            "host_pack_ms": round(pack_ms, 4),
            "total_ms": round(dev_ms + pack_ms, 4),
            "txns_per_sec": round(T / ((dev_ms + pack_ms) / 1e3), 1),
        })
    fitting = [p for p in out if p["total_ms"] <= 1.5]
    chosen = max(fitting, key=lambda p: p["txns_per_sec"]) if fitting else None
    return {"points": out, "production_point": chosen}


#: batch shapes the pipelined service is scanned over. 512 is the serial
#: latency_curve production point (the comparison baseline); the
#: intermediate shapes are where depth>=2 converts device speed into
#: sustained in-budget throughput; the >=1280 shapes are reachable only
#: with the bucket ladder (each pays its own bucket's device time, and the
#: BudgetBatcher rejects them adaptively if the budget says no). The p99
#: budget itself is the resolver_p99_budget_ms knob (docs/perf.md).
HARNESS_SHAPES = (512, 768, 832, 896, 1024, 1280, 1536, 2048)
HARNESS_SCAN_STEPS = 4096   # tunnel RTT amortized to < 0.04 ms/batch


def latency_under_load(host_pack_ms_at_headline: float, curve: dict):
    """Client-observed commit latency under open-loop load through the e2e
    sim cluster, with THIS chip's measured pack/device service times
    injected into the pipelined resolver service (pipeline/): the
    measurement VERDICT r5 asked for — what a client sees, at what
    sustained rate, when `depth` batches are in flight.

    For each compiled batch shape the device time is measured with the
    scan methodology at HARNESS_SCAN_STEPS (long enough that the dev
    tunnel's dispatch RTT inflates the per-batch figure by < 0.15 ms;
    production resolvers sit next to their chip). The sim cluster then
    runs an open-loop Poisson arrival process against serial (depth 1) and
    pipelined (depth >= 2) resolver configurations, offered loads at 90%
    and 96% of each shape's device-paced capacity T / interval. The
    production point is the highest sustained-throughput depth >= 2 point
    whose p99 stays inside the resolver_p99_budget_ms knob."""
    from foundationdb_tpu.pipeline.latency_harness import (
        p99_budget_ms, run_latency_under_load)

    budget = p99_budget_ms()

    pack_per_txn = host_pack_ms_at_headline / CFG.max_txns
    device_ms_by_shape = {}
    for T in HARNESS_SHAPES:
        cfg = ck.KernelConfig(
            key_words=4, capacity=CFG.capacity,
            max_point_reads=2 * T, max_point_writes=2 * T,
            max_reads=64, max_writes=64, max_txns=T, fixpoint=CFG.fixpoint,
        )
        try:
            device_ms_by_shape[T] = measure_scan(cfg, scan_steps=HARNESS_SCAN_STEPS)
        except Exception:
            continue
    if not device_ms_by_shape:
        return None

    points = []

    def run_point(depth: int, T: int, offered: float, util: float) -> dict:
        r = run_latency_under_load(
            depth=depth, batch_txns=T, device_ms=device_ms_by_shape[T],
            pack_ms_per_txn=pack_per_txn,
            offered_txns_per_sec=offered, n_txns=12_000,
            device_ms_by_bucket=device_ms_by_shape, budget_ms=budget,
        )
        d = r.as_dict()
        d["utilization"] = util
        points.append(d)
        return d

    # Serial baseline: the latency_curve production shape, one batch at a
    # time end to end (what today's resolver role delivers to a client).
    # Its capacity is the UN-overlapped cycle: pack + device + commit path.
    if 512 in device_ms_by_shape:
        serial_cycle_ms = device_ms_by_shape[512] + pack_per_txn * 512 + 0.25
        for util in (0.75, 0.85):
            run_point(1, 512, util * 512 / (serial_cycle_ms / 1e3), util)
    # Pipelined: double buffering across the candidate shapes, offered at
    # and just around the device-paced capacity T / interval (open-loop —
    # overload shows up as latency, and the budget filter rejects it).
    for T in HARNESS_SHAPES:
        if T != 512 and T in device_ms_by_shape:
            capacity = T / (max(0.2, device_ms_by_shape[T]) / 1e3)
            for util in (0.97, 1.0, 1.03):
                run_point(2, T, util * capacity, util)

    def in_budget(p):
        return p["errors"] == 0 and p["p99_ms"] <= budget

    candidates = [p for p in points if p["depth"] >= 2 and in_budget(p)]
    production = max(candidates, key=lambda p: p["sustained_txns_per_sec"]) \
        if candidates else None
    # Triple buffering probed at the winning shape: shows whether more
    # in-flight batches buy anything once the device is the bottleneck.
    if production is not None:
        run_point(3, production["batch_txns"],
                  production["offered_txns_per_sec"],
                  production["utilization"])
        candidates = [p for p in points if p["depth"] >= 2 and in_budget(p)]
        production = max(candidates, key=lambda p: p["sustained_txns_per_sec"])
    serial_points = [p for p in points if p["depth"] == 1 and in_budget(p)]
    serial_best = max(serial_points, key=lambda p: p["sustained_txns_per_sec"]) \
        if serial_points else None

    out = {
        "budget_p99_ms": budget,
        "budget_knob": "resolver_p99_budget_ms",
        "scan_steps": HARNESS_SCAN_STEPS,
        "device_ms_by_shape": {str(t): round(v, 4)
                               for t, v in sorted(device_ms_by_shape.items())},
        "points": points,
        "serial_point": serial_best,
        "production_point": production,
    }
    curve_512 = next((p for p in curve.get("points", [])
                      if p.get("batch_txns") == 512), None)
    if production is not None and curve_512 is not None:
        # the acceptance quantity: sustained in-budget txn/s/chip of the
        # pipelined service vs the serial 512-batch latency_curve point.
        # NOTE the curve's device times come from shorter scans (more
        # dispatch-RTT amortized into the serial denominator on a tunneled
        # dev chip); vs_serial_harness below is the methodology-matched
        # ratio (both sides at HARNESS_SCAN_STEPS device times).
        out["vs_serial_512_curve"] = round(
            production["sustained_txns_per_sec"] / curve_512["txns_per_sec"], 3)
    if production is not None and serial_best is not None:
        out["vs_serial_harness"] = round(
            production["sustained_txns_per_sec"]
            / serial_best["sustained_txns_per_sec"], 3)
    return out


def latency_attribution(host_pack_ms_at_headline: float, under_load,
                        loop_floor=None, compile_memory=None):
    """Span-based decomposition of the client-observed commit latency at
    the production point (docs/observability.md): re-runs the e2e harness
    with commit-path span collection enabled (core/trace.py) so the p50/p99
    latency splits into named phase segments — batch wait, version fetch,
    resolver queue wait, host pack, pipeline wait, device dispatch, force,
    log push, network residuals — that sum to the client-observed figure
    (the sum identity is by construction; every segment is measured from
    real span timestamps along the commit path)."""
    from foundationdb_tpu.pipeline.latency_harness import (
        p99_budget_ms, run_latency_under_load)

    production = (under_load or {}).get("production_point")
    if production is not None:
        depth = production["depth"]
        T = production["batch_txns"]
        offered = production["offered_txns_per_sec"]
    else:
        depth, T = 2, 512
        offered = None
    dev_by_shape = {int(t): v for t, v in
                    ((under_load or {}).get("device_ms_by_shape") or {}).items()}
    if T not in dev_by_shape:
        return None
    if offered is None:
        offered = 0.9 * T / (max(0.2, dev_by_shape[T]) / 1e3)
    try:
        r = run_latency_under_load(
            depth=depth, batch_txns=T, device_ms=dev_by_shape[T],
            pack_ms_per_txn=host_pack_ms_at_headline / CFG.max_txns,
            offered_txns_per_sec=offered, n_txns=8_000,
            device_ms_by_bucket=dev_by_shape, budget_ms=p99_budget_ms(),
            collect_spans=True,
        )
    except Exception:
        return None
    if r.attribution is None:
        return None
    out = dict(r.attribution)
    out.update({"depth": depth, "batch_txns": T,
                "offered_txns_per_sec": round(offered, 1),
                "p50_ms": round(r.p50_ms, 3), "p99_ms": round(r.p99_ms, 3)})
    if compile_memory and compile_memory.get("engines"):
        # MEASURED per-bucket device ms (sampled enqueue->ready, the
        # resolver_device_time_sample_rate machinery at 100%) next to the
        # sim's injected figures above — the cross-check that the
        # injected model and the measured engine agree in shape
        out["measured_device_ms_by_bucket"] = {
            mode: eng.get("device_time_ms")
            for mode, eng in compile_memory["engines"].items()}
        out["measured_device_time_source"] = (
            "compile_memory section: sampled enqueue->ready wall "
            "intervals, sample rate 1.0")
    if loop_floor and loop_floor.get("parity_ok"):
        # Device-loop variant (docs/perf.md "Device-resident loop"): the
        # same production point with the device span SPLIT into enqueue /
        # device-resident / drain segments, the host shares injected from
        # loop_floor's measured per-batch figures (scaled pro-rata to
        # this shape). What this proves is the decomposition — the loop's
        # host-side work is the two small named segments, everything else
        # is device-resident — plus the absolute end-to-end figure at the
        # production point. The step-vs-loop SAVING itself is the
        # measured wall-clock delta in the loop_floor section (attached
        # below): the sim injects scan-amortized device times on both
        # sides, so the step path's real per-batch launch+force overhead
        # — exactly what the loop removes — never enters either sim model
        # and the two attributions must not be read as a head-to-head.
        scale = T / max(1, loop_floor["batch_txns"])
        try:
            rl = run_latency_under_load(
                depth=depth, batch_txns=T, device_ms=dev_by_shape[T],
                pack_ms_per_txn=host_pack_ms_at_headline / CFG.max_txns,
                offered_txns_per_sec=offered, n_txns=8_000,
                device_ms_by_bucket=dev_by_shape, budget_ms=p99_budget_ms(),
                dispatch_mode="device_loop",
                queue_enqueue_ms=loop_floor["loop_enqueue_ms_per_batch"] * scale,
                result_drain_ms=loop_floor["loop_decode_ms_per_batch"] * scale,
                collect_spans=True,
            )
        except Exception:
            rl = None
        if rl is not None and rl.attribution is not None:
            loop_att = dict(rl.attribution)
            loop_att.update({
                "p50_ms": round(rl.p50_ms, 3), "p99_ms": round(rl.p99_ms, 3),
                "blocking_syncs": loop_floor["loop_stats"]["blocking_syncs"],
                # the measured saving (tools/floor_bench.run_loop_floor):
                # per-batch HOST wall time, step launch+force vs loop
                # enqueue+poll, identical streams
                "measured_step_host_ms": loop_floor["step_host_ms_per_batch"],
                "measured_loop_host_ms": loop_floor["loop_host_ms_per_batch"],
                "measured_loop_speedup": loop_floor["loop_speedup"],
            })
            out["device_loop"] = loop_att
    return out


#: sub-capacity bucket sizes compiled alongside the top CFG shape for the
#: bucket_ladder section (the resolver_bucket_ladder knob's production
#: default candidate) — chosen so the latency-budget production point can
#: pick a shape that pays its own device time instead of the 4096 pad's.
LADDER_BUCKETS = (512, 1024, 2048)


def bucket_ladder_section(smoke: bool = False):
    """The bucket-ladder proof (docs/perf.md): per-bucket device ms with
    the scan methodology, plus a warmed JaxConflictEngine driven with
    mixed-size batches straddling every bucket boundary — reporting the
    bucket-hit histogram, the fused-scan dispatch histogram, warmup cost,
    and the compile counter split that shows ZERO steady-state compiles
    in the serving path."""
    from foundationdb_tpu.tools.ladder_bench import drive_bucket_ladder

    try:
        sec = drive_bucket_ladder(CFG, list(LADDER_BUCKETS), pool=POOL,
                                  steady_rounds=1 if smoke else 2)
    except Exception:
        return None
    dev_ms = {}
    for b in sec["ladder"]:
        try:
            dev_ms[b] = measure_scan(CFG.bucket(b),
                                     scan_steps=64 if smoke else 256)
        except Exception:
            continue
    sec["device_ms_by_bucket"] = {str(t): round(v, 4)
                                  for t, v in sorted(dev_ms.items())}
    sec["device_txns_per_sec_by_bucket"] = {
        str(t): round(t / (v / 1e3), 1) for t, v in sorted(dev_ms.items())}
    return sec


def history_floor_section(smoke: bool = False):
    """The history-search floor proof (docs/perf.md "History search
    modes"): device ms/batch vs boundary-table occupancy n at a FIXED
    512-txn batch, for both history-query strategies. The fused_sort path
    re-sorts the capacity-H table with every step — the ~1.1 ms device
    floor BENCH_r05's latency curve showed at small batches — while
    bsearch replaces it with a batch-only sort + vectorized binary search
    whose cost tracks the batch. tools/floor_bench.py owns the
    methodology (synthesized table at exact occupancy, read-only batches,
    scan timing, zero-recompile counters); `make bench-smoke` drives the
    same sweep on CPU.

    The `apply` sub-section (recorded since BENCH_r12) is the MAINTENANCE
    floor (docs/perf.md "Incremental history maintenance"): isolated
    `apply_writes_and_gc` cost vs occupancy, monolithic vs tiered, at the
    512-txn production point with SMALL-TOUCH batches (read-mostly
    transactions, 64 point-write rows against a 24k-row table — the
    regime the tiered structure exists for; a write-heavy batch touching
    ~capacity/11 rows per apply amortizes to parity and is not the
    claim). Tiered apply must scale with the batch, not the capacity."""
    from foundationdb_tpu.tools.floor_bench import run_apply_sweep, run_floor_sweep

    # pallas is the production fixpoint; the xla fallback keeps the
    # section alive on backends without the fused kernel (CPU runs) —
    # the fixpoint choice is mode-independent, so the floor gap it
    # measures is the same either way. The cpu profile goes straight to
    # xla: the pallas interpreter does not raise, it just crawls.
    for fixpoint in (("xla",) if PROFILE == "cpu" else ("pallas", "xla")):
        cfg = ck.KernelConfig(
            key_words=4, capacity=CFG.capacity,
            max_point_reads=1024, max_point_writes=1024,
            max_reads=64, max_writes=64, max_txns=512, fixpoint=fixpoint,
        )
        try:
            out = run_floor_sweep(
                cfg, scan_steps=64 if (smoke or PROFILE == "cpu") else 256)
        except Exception:
            continue
        try:
            apply_cfg = ck.KernelConfig(
                key_words=4, capacity=CFG.capacity,
                max_point_reads=1024, max_point_writes=64,
                max_reads=64, max_writes=16, max_txns=512,
                fixpoint=fixpoint,
            )
            out["apply"] = run_apply_sweep(
                apply_cfg, scan_steps=48 if (smoke or PROFILE == "cpu") else 128)
        except Exception:
            out["apply"] = None
        return out
    return None


def loop_floor_section():
    """The device-resident loop proof (docs/perf.md "Device-resident
    loop"): per-batch HOST time, step dispatch vs the loop engine, at the
    production point (512-txn batches, depth-2 pipeline) over identical
    streams — PR 5 left this figure dispatch-shaped, and this section
    shows what the persistent on-device server step + non-blocking result
    ring buy back. tools/floor_bench.run_loop_floor owns the methodology
    (identical streams, verdict-parity canary, sync accounting:
    blocking_syncs MUST be 0)."""
    from foundationdb_tpu.tools.floor_bench import run_loop_floor

    cfg = ck.KernelConfig(
        key_words=4, capacity=CFG.capacity,
        max_point_reads=1024, max_point_writes=1024,
        max_reads=64, max_writes=64, max_txns=512,
    )
    try:
        return run_loop_floor(cfg, n_batches=32, pool=POOL // 4)
    except Exception:
        return None


def conflict_heat_section():
    """The keyspace-heat proof (docs/observability.md "Keyspace heat &
    occupancy"): a Zipf workload fleet (s in {0, 0.9, 1.2}, ranks mapped
    through a seeded permutation like hashed production keys) drives a
    heat-on engine at the 512-txn production point — the measured
    hot-range concentration must increase with s, the suggested split
    points must balance the measured write load within 20% across 8
    shards at s = 0.9, the heat-on device time must stay within 3% of
    heat-off (interleaved scan timing), and the on/off abort-set parity
    is witnessed in the artifact. tools/heat_bench.py owns the
    methodology; `make heat-smoke` drives the same code at toy sizes."""
    from foundationdb_tpu.tools.heat_bench import run_conflict_heat

    cfg = ck.KernelConfig(
        key_words=4, capacity=CFG.capacity,
        max_point_reads=1024, max_point_writes=1024,
        max_reads=64, max_writes=64, max_txns=512,
    )
    try:
        return run_conflict_heat(
            cfg, pool=POOL // 4, n_batches=16 if PROFILE == "cpu" else 24,
            overhead_scan_steps=64 if PROFILE == "cpu" else 128)
    except Exception:
        return None


def compile_memory_section():
    """The compile & memory ledger proof (docs/observability.md
    "Performance observatory"): a laddered step engine and a device-loop
    engine are warmed and then driven with mixed-size traffic at 100%
    device-time sampling. The section records every compile's duration +
    cost_analysis flops/bytes + memory_analysis peak HBM per (bucket,
    search mode, dispatch mode), the engines' interval-table footprint
    (the PR 11 `state_bytes` gauge's quantity), the sampled measured
    per-bucket device ms, and the zero-steady-state-compile counter WITH
    sampling baked in — the before/after evidence the EngineSpec refactor
    and the PAM history table (ROADMAP items 2-3) will be judged by."""
    from foundationdb_tpu.ops.device_loop import DeviceLoopEngine
    from foundationdb_tpu.ops.host_engine import JaxConflictEngine
    from foundationdb_tpu.tools.floor_bench import _CompileCounter
    from foundationdb_tpu.tools.ladder_bench import make_point_txns

    cfg = ck.KernelConfig(
        key_words=4, capacity=CFG.capacity,
        max_point_reads=1024, max_point_writes=1024,
        max_reads=64, max_writes=64, max_txns=512,
    )
    out = {"engines": {}, "batch_txns": cfg.max_txns,
           "capacity": cfg.capacity}
    peak = 0
    steady_total = 0
    monitored = True
    rng = np.random.default_rng(2029)
    try:
        builds = (
            ("step", lambda: JaxConflictEngine(
                cfg, ladder=[128, 256], scan_sizes=(2,),
                device_time_sample_rate=1.0)),
            ("loop", lambda: DeviceLoopEngine(
                cfg, ladder=[128, 256], device_time_sample_rate=1.0)),
        )
        for label, build in builds:
            eng = build()
            eng.warmup()
            counter = _CompileCounter()
            try:
                version = 1_000
                for _ in range(2):
                    for n in (64, 128, 200, 512):
                        txns = make_point_txns(n, POOL // 8, rng, version)
                        version += max(64, n)
                        eng.resolve(txns, version, max(0, version - 100_000))
                drain = getattr(eng, "drain_loop", None)
                if drain is not None:
                    drain()
            finally:
                # an aborted drive must still unregister the listener, or
                # every later section's compiles tick a dead counter
                steady = counter.close()
            if steady is None:
                monitored = False
            else:
                steady_total += steady
            snap = eng.perf_ledger.snapshot(max_rows=32)
            state_bytes = int(sum(
                getattr(leaf, "nbytes", 0)
                for leaf in jax.tree.leaves(eng.state)))
            out["engines"][label] = {
                "ledger": snap,
                "state_bytes": state_bytes,
                "warmup_ms": round(eng.perf.warmup_ms, 1),
                "device_time_ms": {
                    str(b): v for b, v in
                    sorted(eng.perf.device_time_ms_by_bucket().items())},
                "device_time_samples": sum(
                    d["samples"] for d in eng.perf.device_time.values()),
                "steady_state_compiles": steady,
            }
            peak = max(peak, snap.get("peak_bytes") or 0)
    except Exception:
        return None
    out["peak_hbm_bytes"] = peak
    out["steady_state_compiles"] = steady_total if monitored else None
    return out


def served_under_chaos_section():
    """The millions-of-users serving campaign's capacity model
    (docs/real_cluster.md): a wall-clock Zipf-skew sweep through the REAL
    transport with the network nemesis active — per skew s in {0, 0.9,
    1.2}, the same overloaded serving point with per-tenant admission
    control ON (p99 must hold inside the wall-clock budget) and OFF (the
    uncontrolled queue must blow it — degradation demonstrated, not
    assumed), plus a no-nemesis baseline converting the in-budget
    sustained rate into users-served. Runs on CPU + localhost sockets
    regardless of the bench chip; the budget is the knob product
    resolver_p99_budget_ms x real_chaos_budget_factor (the wall-clock
    serving point — see core/knobs.py)."""
    try:
        from foundationdb_tpu.real.nemesis import run_served_under_chaos

        return run_served_under_chaos()
    except Exception as e:  # noqa: BLE001 — a socketless/odd environment
        #                     must not kill the chip bench (sibling
        #                     sections guard the same way)
        return {"error": f"{type(e).__name__}: {e}"}


def served_while_resharding_section():
    """The elastic capacity model (ROADMAP item 4 follow-up,
    docs/elasticity.md): the served_under_chaos serving point driven
    through the elastic resolver group under a DRIFTING Zipf hot spot,
    once with the heat-driven reshard controller ACTIVE and once static —
    users-served per chip WHILE ranges split/move live (admission
    clamped during handoffs, blackouts pausing the frozen range) vs. the
    static figure. Wall-clock + oracle engines, chip-independent like
    its sibling section."""
    try:
        from foundationdb_tpu.real.nemesis import run_served_while_resharding

        return run_served_while_resharding()
    except Exception as e:  # noqa: BLE001 — a socketless/odd environment
        #                     must not kill the chip bench
        return {"error": f"{type(e).__name__}: {e}"}


def conflict_scheduling_section():
    """The conflict-aware admission A/B (docs/scheduling.md): the same
    contended Zipf-1.2 wall-clock serving point — same seed, same fleet,
    oracle engines, no injected chaos — with the scheduler OFF and ON.
    Reports both rows (abort_frac, served txn/s, p99, parity mismatches)
    plus abort_frac_reduction, served_tps_ratio and goal_met (reduction
    >= 50% at equal-or-better served txn/s with bit-for-bit dispatch
    parity through the clean oracle in both arms). Wall-clock + CPU like
    its chaos siblings; `make sched-smoke` drives the same mechanisms at
    toy sizes in seconds."""
    try:
        from foundationdb_tpu.real.nemesis import run_conflict_scheduling

        return run_conflict_scheduling()
    except Exception as e:  # noqa: BLE001 — a socketless/odd environment
        #                     must not kill the chip bench
        return {"error": f"{type(e).__name__}: {e}"}


def scenario_atlas_section():
    """The scenario atlas (docs/scenarios.md, recorded from BENCH_r11):
    all six named production recipes — flash_sale, payment_ledger,
    secondary_index, task_queue, timeseries_ingest, session_cache —
    each a full wall-clock chaos campaign (elastic group, one injected
    partition, watchdog + spans + journal parity) judged against its
    own SLO contract rows. Per-scenario headline metrics land under
    `scenarios.<name>.*`; tools/bench_history.py gates every scenario's
    `slo_pass`, so a regression in ANY one recipe fails the trend gate.
    `make atlas-smoke` drives two recipes at miniature scale in seconds."""
    try:
        from foundationdb_tpu.real.scenarios import run_scenario_atlas

        return run_scenario_atlas()
    except Exception as e:  # noqa: BLE001 — a socketless/odd environment
        #                     must not kill the chip bench
        return {"error": f"{type(e).__name__}: {e}"}


def recovery_section():
    """The crash-stop recovery economics (docs/fault_tolerance.md
    "Crash-stop recovery"): cold vs progcache-warm rewarm of the bucket
    ladder in fresh subprocesses (the >= 5x acceptance bar, zero warm
    compiles), snapshot + differential journal replay vs full-journal
    replay over the same recorded stream (parity witnessed on both
    arms), and one real kill -9 campaign's measured recovery blackout
    vs resolver_recovery_budget_ms. tools/recovery_bench.py owns the
    methodology; wall-clock + CPU like the chaos siblings."""
    try:
        from foundationdb_tpu.tools.recovery_bench import run_recovery_bench

        return run_recovery_bench()
    except Exception as e:  # noqa: BLE001 — a socketless/odd environment
        #                     must not kill the chip bench
        return {"error": f"{type(e).__name__}: {e}"}


def sharded_cpu_numbers():
    """S=8 key-range shards over the 8-device virtual CPU mesh vs S=1 on
    the same host, end-to-end through the columnar native router (the
    scaling-shape proxy; multi-chip hardware is not available here). This
    machine has ONE physical core, so the 8 'devices' time-share it: the
    ratio reported is a TOTAL-COMPUTE ratio — on real chips each shard runs
    on its own silicon and the per-shard wall time is what parallelizes.
    Runs tools/sharded_bench.py as a subprocess with the CPU platform
    forced; returns its JSON dict or None."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        r = subprocess.run(
            [sys.executable, "-m", "foundationdb_tpu.tools.sharded_bench"],
            capture_output=True, timeout=900, env=env, text=True,
        )
        if r.returncode != 0:
            return None
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception:
        return None


def sharded_measured_numbers():
    """The MEASURED mesh-resolution numbers (parallel/mesh_engine.py):
    per-width scan/exchange intervals from the engine's own result-ring
    stamps, a dedicated AOT psum-chain collective measurement at each
    mesh width (replacing sharded_tpu_weak_scale's estimated 0.15 ms ICI
    figure with a measured one — on CPU it measures the XLA host
    collective, tagged by platform so bench_history never compares it
    against chip-era estimates), oracle parity at every width, and the
    overlapped-vs-serialized A/B the double-buffered exchange ring must
    win. Runs tools/mesh_bench.py as a subprocess with 8 forced host
    devices; returns its JSON dict or None."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        r = subprocess.run(
            [sys.executable, "-m", "foundationdb_tpu.tools.mesh_bench"],
            capture_output=True, timeout=900, env=env, text=True,
        )
        if r.returncode != 0:
            return None
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception:
        return None


def native_baseline_txns_per_sec():
    """The C++ resolver on one CPU core, same transaction shape (the
    `-r skiplisttest` baseline the kernel is judged against). Wire blocks
    are pre-encoded outside the timed loop — the comparison is engine vs
    engine, with host packing charged separately on both sides."""
    try:
        from foundationdb_tpu.tools.skiplist_bench import make_batches
        from foundationdb_tpu.ops.native_engine import NativeConflictEngine

        eng = NativeConflictEngine()
    except Exception:
        return None
    batches = make_batches(40, 1000, POOL, 7)
    encoded = [
        ([t.conflict_wire_block() for t in txns],
         [t.read_snapshot for t in txns], now, oldest)
        for txns, now, oldest in batches
    ]
    eng.resolve_wire(*encoded[0])
    t0 = time.perf_counter()
    for blocks, snaps, now, oldest in encoded[1:]:
        eng.resolve_wire(blocks, snaps, now, oldest)
    return round((len(encoded) - 1) * 1000 / (time.perf_counter() - t0))


def host_packing_ms_per_batch(arena: bool = False) -> float:
    """End-to-end cost of the host side of a resolve: transactions off the
    wire -> fixed-shape device arrays. Transactions arrive as columnar
    conflict-wire blocks (core/wire.py; the client serializes its commit
    request once, exactly as the reference resolver receives a serialized
    ResolveTransactionBatchRequest), so the resolver-side work measured here
    is: concatenate blocks + two native passes + numpy int lanes
    (ops/host_engine.wire_pass1 / wire_chunk_arrays). The e2e estimate
    charges this on top of the device scan time (VERDICT r1: 'end-to-end
    resolver throughput, host routing + packing included')."""
    from foundationdb_tpu.core import wire as fwire
    from foundationdb_tpu.ops import host_engine as he

    rng = np.random.default_rng(7)
    T = CFG.max_txns
    keys = [b"bench/%010d" % k for k in rng.integers(0, POOL, size=T * 4)]

    class _R:
        __slots__ = ("begin", "end")

        def __init__(self, k):
            self.begin, self.end = k, k + b"\x00"

    blocks = [
        fwire.conflict_wire(
            [_R(keys[4 * t]), _R(keys[4 * t + 1])],
            [_R(keys[4 * t + 2]), _R(keys[4 * t + 3])],
        )
        for t in range(T)
    ]
    snaps = np.full((T,), 100, np.int64)
    window = 4 * CFG.key_words
    pool_arena = he.HostPackArena() if arena else None
    REPS = 10
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        bufs = lease = None
        if pool_arena is not None:
            # serving path: lease pooled buffers instead of np.zeros-ing
            # ~10 padded arrays per chunk (first rep allocates — a pool
            # miss; min over reps is the steady-state reuse cost)
            bufs, lease = pool_arena.lease(CFG)
        p1 = he.wire_pass1(window, blocks)
        assert p1 is not None, "native wire parser unavailable"
        blob, offs, rp_cnt, wp_cnt = p1
        snap_rel = np.maximum(snaps - 0, -1).astype(np.int32)
        too_old = (snaps < 0) & (rp_cnt > 0)
        skip = too_old.astype(np.uint8)
        eff_r = np.where(too_old, 0, rp_cnt).astype(np.int32)
        he.wire_chunk_arrays(
            CFG, blob, offs, 0, T, skip, snap_rel, eff_r, 1000, 0, bufs=bufs)
        if lease is not None:
            lease.release()
        # min over reps: the host share is a fixed amount of C + numpy
        # work; anything above the minimum is scheduler noise on this
        # single-core box, not cost the resolver would pay
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def parity_measurement_set() -> bool:
    """BASELINE.json's parity configs, bit-exactness asserted at bench time:
    Cycle-shaped RMW, WriteDuringRead-style mixed ops, Zipf RandomReadWrite,
    AtomicOps + range-clears. Small caps so compile stays cheap; any verdict
    mismatch vs the reference-exact oracle fails the bench."""
    import random as pyrandom

    from foundationdb_tpu.core.types import CommitTransaction, KeyRange
    from foundationdb_tpu.ops.host_engine import JaxConflictEngine
    from foundationdb_tpu.ops.oracle import OracleConflictEngine

    cfg = ck.KernelConfig(key_words=4, capacity=4096, max_txns=64,
                          max_reads=128, max_writes=128,
                          fixpoint=CFG.fixpoint)  # the profile's fixpoint
    #                       (pallas on chip; xla on the cpu profile, where
    #                       the interpreter would crawl)
    rng = pyrandom.Random(99)

    def key(pool, zipf=False):
        if zipf:
            i = int((rng.random() ** 3) * pool)
        else:
            i = rng.randrange(pool)
        return b"p/%06d" % i

    def txn(style, v):
        t = CommitTransaction(read_snapshot=max(0, v - rng.randrange(1, 3000)))
        if style == "cycle":
            ks = sorted(key(64) for _ in range(3))
            for k in ks:
                t.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
                t.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
        elif style == "wdr":
            for _ in range(rng.randrange(1, 4)):
                a, b = sorted([key(256), key(256)])
                t.read_conflict_ranges.append(KeyRange(a, b + b"\x00"))
            for _ in range(rng.randrange(1, 3)):
                t.write_conflict_ranges.append(KeyRange(key(256), key(256) + b"\x00"))
        elif style == "zipf":
            for _ in range(9):
                k = key(4096, zipf=True)
                t.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            k = key(4096, zipf=True)
            t.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
        else:  # atomic ops + range clears
            k = key(512)
            t.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            if rng.random() < 0.4:
                a, b = sorted([key(512), key(512)])
                t.write_conflict_ranges.append(KeyRange(a, b + b"\x00"))
        return t

    for style in ("cycle", "wdr", "zipf", "atomic"):
        eng, ora = JaxConflictEngine(cfg), OracleConflictEngine()
        v = 1000
        for _ in range(8):
            txns = [txn(style, v) for _ in range(rng.randrange(2, 16))]
            v += rng.randrange(200, 1500)
            got = [int(x) for x in eng.resolve(txns, v, max(0, v - 5_000_000))]
            want = [int(x) for x in ora.resolve(txns, v, max(0, v - 5_000_000))]
            if got != want:
                return False
    return True


if __name__ == "__main__":
    main()
